"""Streaming chunked part sync on the reference wire protocol.

Implements banyandb.cluster.v1.ChunkedSyncService/SyncPart (bidi stream;
/root/reference/api/proto/banyandb/cluster/v1/rpc.proto,
banyand/queue/pub/chunked_sync.go sender + sub side receiver): sealed
parts ship as raw binary 1 MiB chunks with per-chunk CRC32 and a
files-within-parts layout (PartInfo/FileInfo offsets), replacing the
round-1 base64-in-JSON unary path — no 33% inflation, no whole-part
memory residency on the sender, streaming backpressure for free.

Wire layout: the byte stream is the concatenation of each part's files
(FileInfo.offset relative to the part's start, parts concatenated in
PartInfo order); chunk boundaries are arbitrary.  parts_info rides the
completion chunk.
"""

from __future__ import annotations

import json
import time
import uuid
import zlib
from pathlib import Path
from typing import Callable, Iterable

import grpc

from banyandb_tpu.api import pb

SERVICE = "banyandb.cluster.v1.ChunkedSyncService"
METHOD = f"/{SERVICE}/SyncPart"
CHUNK_SIZE = 1 << 20
API_VERSION = "1.0"


def _crc(data: bytes) -> str:
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


# -- failure injection -------------------------------------------------------
#
# Deterministic fault injection for sync paths, mirroring the reference's
# queue.ChunkedSyncFailureInjector contract (banyand/queue/queue.go:230).
# Two sources, explicit registration winning: tests may register an
# injector directly; otherwise the process-global fault plane
# (cluster/faults.py, BYDB_FAULTS schedule) drives the same hooks when
# its schedule names sync faults.  Production with no plane configured
# injects nothing.


class SyncFailureInjector:
    """Override any subset; the default injects nothing."""

    def before_sync(self, part_dirs) -> tuple[bool, str]:
        """-> (short_circuit, error): True fails the sync before the
        stream opens (queue.go:234 BeforeSync analog)."""
        return (False, "")

    def mutate_request(self, req):
        """Per-chunk hook: return a (possibly corrupted) request, or
        raise to kill the stream mid-flight (wire-level fault)."""
        return req


_failure_injector: SyncFailureInjector | None = None


def register_failure_injector(inj: SyncFailureInjector | None) -> None:
    global _failure_injector
    _failure_injector = inj


def clear_failure_injector() -> None:
    register_failure_injector(None)


# -- server ----------------------------------------------------------------


def sync_method_handler(install_cb: Callable):
    """-> grpc stream_stream handler for SyncPart.

    install_cb(meta: SyncMetadata, parts: list[(PartInfo, {file: bytes})])
    is called once per completed session; it raises to fail the sync.
    """
    rpcpb = pb.cluster_rpc_pb2

    def sync_part(request_iterator, context):
        import os
        import tempfile

        meta = None
        expected = 0
        total = 0
        t0 = time.monotonic()
        # chunks spool to disk as they arrive, so receiver memory stays
        # O(chunk) regardless of part size; per-file slices are read back
        # at install (peak = largest single column file, not the part)
        spool = tempfile.NamedTemporaryFile(
            prefix="bydb-sync-", suffix=".spool", delete=False
        )
        try:
            for req in request_iterator:
                if req.chunk_index != expected:
                    yield rpcpb.SyncPartResponse(
                        session_id=req.session_id,
                        chunk_index=req.chunk_index,
                        status=3,  # SYNC_STATUS_CHUNK_OUT_OF_ORDER
                        error=f"expected chunk {expected}, got {req.chunk_index}",
                    )
                    return
                if req.chunk_data and _crc(req.chunk_data) != req.chunk_checksum:
                    yield rpcpb.SyncPartResponse(
                        session_id=req.session_id,
                        chunk_index=req.chunk_index,
                        status=2,  # SYNC_STATUS_CHUNK_CHECKSUM_MISMATCH
                        error="chunk CRC mismatch",
                    )
                    return
                if req.WhichOneof("content") == "metadata":
                    meta = req.metadata
                spool.write(req.chunk_data)
                total += len(req.chunk_data)
                expected += 1
                if req.WhichOneof("content") == "completion":
                    if meta is None:
                        yield rpcpb.SyncPartResponse(
                            session_id=req.session_id,
                            chunk_index=req.chunk_index,
                            status=4,  # SYNC_STATUS_SESSION_NOT_FOUND
                            error="completion without metadata",
                        )
                        return
                    spool.flush()
                    # split the stream into parts/files per the layout
                    parts = []
                    offset = 0
                    with open(spool.name, "rb") as rd:
                        for pi in req.parts_info:
                            files = {}
                            end = offset
                            for fi in pi.files:
                                rd.seek(offset + fi.offset)
                                files[fi.name] = rd.read(fi.size)
                                end = max(end, offset + fi.offset + fi.size)
                            parts.append((pi, files))
                            offset = end
                    results = []
                    ok = True
                    try:
                        install_cb(meta, parts)
                        results = [
                            rpcpb.PartResult(
                                success=True,
                                bytes_processed=sum(len(b) for b in f.values()),
                            )
                            for _, f in parts
                        ]
                    except Exception as e:  # noqa: BLE001 - reported in-band
                        ok = False
                        results = [rpcpb.PartResult(success=False, error=str(e))]
                    yield rpcpb.SyncPartResponse(
                        session_id=req.session_id,
                        chunk_index=req.chunk_index,
                        status=5 if ok else 4,  # COMPLETE | SESSION_NOT_FOUND
                        error="" if ok else results[0].error,
                        sync_result=rpcpb.SyncResult(
                            success=ok,
                            total_bytes_received=total,
                            duration_ms=int((time.monotonic() - t0) * 1000),
                            chunks_received=expected,
                            parts_received=len(parts),
                            parts_results=results,
                        ),
                    )
                    return
                yield rpcpb.SyncPartResponse(
                    session_id=req.session_id,
                    chunk_index=req.chunk_index,
                    status=1,  # SYNC_STATUS_CHUNK_RECEIVED
                )
        finally:
            spool.close()
            try:
                os.unlink(spool.name)
            except OSError:
                pass

    return grpc.stream_stream_rpc_method_handler(
        sync_part,
        request_deserializer=rpcpb.SyncPartRequest.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def generic_handler(install_cb: Callable):
    return grpc.method_handlers_generic_handler(
        SERVICE, {"SyncPart": sync_method_handler(install_cb)}
    )


# -- client ----------------------------------------------------------------


def _part_layout(part_dir: Path) -> tuple[list, list[Path], int]:
    """-> (FileInfo list, file paths in stream order, total bytes) for one
    part dir — stat-only, no file contents loaded."""
    rpcpb = pb.cluster_rpc_pb2
    files = []
    paths = []
    off = 0
    for f in sorted(part_dir.iterdir()):
        if not f.is_file():
            continue
        size = f.stat().st_size
        files.append(rpcpb.FileInfo(name=f.name, offset=off, size=size))
        paths.append(f)
        off += size
    return files, paths, off


def parse_epoch_topic(topic: str) -> "tuple[str, int | None]":
    """Split a ``<topic>@epoch=N`` wire topic -> (bare topic, epoch or
    None).  The placement-epoch fence rides the topic string because
    the SyncMetadata proto has no spare field; receivers parse it here
    and feed their EpochRecord (docs/robustness.md "Elastic cluster")."""
    base, sep, tail = topic.partition("@epoch=")
    if not sep:
        return topic, None
    try:
        return base, int(tail)
    except ValueError:
        return base, None


def sync_part_dirs(
    channel: grpc.Channel,
    part_dirs: Iterable[str | Path],
    *,
    group: str,
    shard_id: int,
    topic: str = "measure-part-sync",
    sender_node: str = "liaison",
    chunk_size: int = CHUNK_SIZE,
    timeout: float = 120.0,
    placement_epoch: "int | None" = None,
):
    """Ship sealed part dirs over one SyncPart stream; -> SyncResult.

    placement_epoch: optional epoch fence — stamped as a ``@epoch=N``
    topic suffix so the receiver can reject sessions from a sender
    routing on a superseded placement map.

    Raises TransportError on any non-OK chunk status or stream failure.
    """
    from banyandb_tpu.cluster import faults
    from banyandb_tpu.cluster.rpc import TransportError

    rpcpb = pb.cluster_rpc_pb2
    part_dirs = [Path(p) for p in part_dirs]
    injector = (
        _failure_injector
        if _failure_injector is not None
        else faults.plane_sync_injector()
    )
    if injector is not None:
        short, err = injector.before_sync(part_dirs)
        if short:
            raise TransportError(f"sync failure injected: {err}")
    session = uuid.uuid4().hex
    parts_info = []
    file_lists: list[list[Path]] = []
    total_bytes = 0
    for pd in part_dirs:
        files, paths, nbytes = _part_layout(pd)
        meta = {}
        try:
            meta = json.loads((pd / "metadata.json").read_bytes())
        except (OSError, json.JSONDecodeError):
            pass
        parts_info.append(
            rpcpb.PartInfo(
                id=int(pd.name.split("-")[-1], 16) if "-" in pd.name else 0,
                files=files,
                uncompressed_size_bytes=nbytes,
                total_count=int(meta.get("total_count", 0)),
                blocks_count=int(meta.get("blocks", 0)),
                min_timestamp=int(meta.get("min_ts", 0)),
                max_timestamp=int(meta.get("max_ts", 0)),
                part_type=topic.split("-")[0],
            )
        )
        file_lists.append(paths)
        total_bytes += nbytes

    def requests():
        # metadata and completion share a oneof, so the stream is always
        # [metadata+data chunk, data chunks..., completion-only chunk].
        # Files are read incrementally: at most ~one chunk is resident on
        # the sender at a time (parts may be big; the spool is on disk).
        idx = 0

        def mk(data: bytes):
            nonlocal idx
            req = rpcpb.SyncPartRequest(
                session_id=session,
                chunk_index=idx,
                chunk_data=data,
                chunk_checksum=_crc(data),
                version_info=rpcpb.VersionInfo(api_version=API_VERSION),
            )
            if idx == 0:
                req.metadata.group = group
                req.metadata.shard_id = shard_id
                req.metadata.topic = (
                    f"{topic}@epoch={placement_epoch}"
                    if placement_epoch is not None
                    else topic
                )
                req.metadata.total_parts = len(parts_info)
                req.metadata.sender_node = sender_node
            idx += 1
            if injector is not None:
                req = injector.mutate_request(req)
            return req

        buf = bytearray()
        for paths in file_lists:
            for path in paths:
                with open(path, "rb") as fh:
                    while True:
                        piece = fh.read(chunk_size)
                        if not piece:
                            break
                        buf.extend(piece)
                        while len(buf) >= chunk_size:
                            yield mk(bytes(buf[:chunk_size]))
                            del buf[:chunk_size]
        if buf or idx == 0:
            yield mk(bytes(buf))
        fin = rpcpb.SyncPartRequest(
            session_id=session,
            chunk_index=idx,
            chunk_checksum=_crc(b""),
        )
        fin.parts_info.extend(parts_info)
        fin.completion.total_bytes_sent = total_bytes
        fin.completion.total_parts_sent = len(parts_info)
        fin.completion.total_chunks = idx + 1
        if injector is not None:
            fin = injector.mutate_request(fin)
        yield fin

    call = channel.stream_stream(
        METHOD,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=rpcpb.SyncPartResponse.FromString,
    )
    final = None
    try:
        for resp in call(requests(), timeout=timeout):
            if resp.status not in (1, 5):  # RECEIVED | COMPLETE
                raise TransportError(
                    f"sync chunk {resp.chunk_index} failed: "
                    f"status={resp.status} {resp.error}"
                )
            if resp.status == 5:
                final = resp.sync_result
    except grpc.RpcError as e:
        raise TransportError(f"sync stream failed: {e.code()}") from e
    if final is None or not final.success:
        raise TransportError(
            f"sync incomplete: {final.parts_results[0].error if final and final.parts_results else 'no completion'}"
        )
    return final
