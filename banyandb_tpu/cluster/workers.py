"""Shard-owning worker processes: the multi-process data plane
(docs/performance.md "Multi-process data plane").

The measured ceiling on multi-client serving is one CPython process
(docs/load_r07.json: 4 closed-loop queriers convoy to ~200 ms p50 while
the same queries served in isolation take 42.5 ms).  This module frees
the GIL by mapping shard ownership to worker subprocesses:

- each worker runs a full :class:`~banyandb_tpu.cluster.data_node.DataNode`
  over its OWN directory tree (``<root>/workers/w00i``) — parts,
  memtables, flush/merge/retention loops and streamagg windows are
  single-owner per process, exactly like a cluster data node's;
- the parent speaks to workers over a framed-JSON socketpair with the
  SAME topic envelopes the liaison→data-node wire uses, so a worker is
  just one more scatter leg: :class:`WorkerTransport` plugs the pipe
  into the ordinary :class:`~banyandb_tpu.cluster.liaison.Liaison`,
  which contributes shard placement, scatter/merge through
  ``combine/finalize_partials``, the ``_QueryGuard`` deadline budget,
  one failover round, ``degraded`` markers, and span-subtree grafting —
  none of it reimplemented here;
- ingest partitions by the existing shard hash
  (``hashing.series_id % shard_num``, shard → ``shard % n`` worker) and
  forwards to the owning worker;
- every measure write is journaled in the parent BEFORE forwarding
  (handoff-style): a SIGKILLed worker restarts, replays the journal
  from the last flush watermark, and reloads its streamagg registry
  AFTER the replay — so no acked write is lost and windows never
  double-fold (rows in both a flushed part and the journal collapse in
  the backfill's (series, ts, version) dedup).  The journal trims on
  explicit worker flushes (the watermark = last seq the worker had
  applied when the flush drained its memtables).  ALL flushes are
  parent-driven (the supervisor ticks them on the single-process
  loop's cadence; workers run their lifecycle with local_flush=False):
  a worker-local drain would persist journaled rows without trimming
  them, and the replay after a crash would re-append stream/trace
  elements, which have no version dedup to collapse the copies.

Crash-durability contract: the journal lives in the PARENT process, so
worker death loses nothing acked; parent death loses at most the
untrimmed journal window — identical to the single-process layout's
memtable loss window.  ``BYDB_WORKERS=0`` restores that layout exactly
(see server.py), with result JSON pinned byte-identical across modes.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
import socket
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Optional

from banyandb_tpu.cluster import faults, serde
from banyandb_tpu.cluster.bus import Topic
from banyandb_tpu.cluster.liaison import Liaison
from banyandb_tpu.cluster.node import NodeInfo
from banyandb_tpu.cluster.rpc import TransportError, _error_kind
from banyandb_tpu.utils import hashing, procreg
from banyandb_tpu.utils.envflag import env_int

log = logging.getLogger("banyandb.workers")

CTL_TOPIC = "worker-ctl"

# Topics the worker executes on its single ordered writer thread, in
# arrival order: the parent's per-worker journal seq therefore matches
# the worker's apply order, which is what makes the flush watermark a
# sound trim point.
ORDERED_TOPICS = frozenset(
    {
        Topic.MEASURE_WRITE.value,
        Topic.MEASURE_WRITE_COLUMNS.value,
        Topic.STREAM_WRITE.value,
        Topic.TRACE_WRITE.value,
        CTL_TOPIC,
    }
)

_SPAWN_TIMEOUT_S = 120.0
_WRITE_TIMEOUT_S = 30.0
_CTL_TIMEOUT_S = 120.0
_HDR = struct.Struct(">I")


# -- framing -----------------------------------------------------------------


def _send_frame(
    sock: socket.socket,
    lock: threading.Lock,
    obj: Optional[dict] = None,
    *,
    data: Optional[bytes] = None,
) -> None:
    if data is None:
        data = json.dumps(obj).encode()
    with lock:
        sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    body = _recv_exact(sock, _HDR.unpack(hdr)[0])
    if body is None:
        return None
    return json.loads(body)


# -- parent side: one worker ---------------------------------------------------


class WorkerClient:
    """Parent-side handle on one worker subprocess: spawn, framed-JSON
    RPC with the bus envelope contract, SIGKILL for chaos, reaping."""

    def __init__(self, name: str, root: Path):
        self.name = name
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._send_lock = threading.Lock()
        self._pending_lock = threading.Lock()
        self._pending: dict[int, dict] = {}
        self._ids = itertools.count(1)
        self._dead = threading.Event()
        self._ready = threading.Event()
        self.flush_wm = 0  # set from the ready banner (persisted wm)
        parent_sock, child_sock = socket.socketpair()
        self._sock = parent_sock
        env = dict(os.environ)
        # the worker replays BEFORE loading its streamagg registry (see
        # module docstring); it must also never spawn a pool of its own
        env["BYDB_STREAMAGG_AUTOLOAD"] = "0"
        env["BYDB_WORKERS"] = "0"
        if not env.get("BYDB_COMPILE_CACHE_DIR"):
            # one shared persistent XLA cache for the whole fleet: the
            # second worker's first plan compile is a disk hit
            env["BYDB_COMPILE_CACHE_DIR"] = str(
                self.root.parent / "compile-cache"
            )
        pkg_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH", "")) if p
        )
        self._log = open(  # bdlint: disable=resource-hygiene --
            # owned for the worker's lifetime; close() closes it
            self.root / "worker.log", "ab"
        )
        try:
            self.proc = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "banyandb_tpu.cluster.workers",
                    "--fd",
                    str(child_sock.fileno()),
                    "--root",
                    str(self.root),
                    "--name",
                    name,
                ],
                pass_fds=(child_sock.fileno(),),
                stdout=self._log,
                stderr=subprocess.STDOUT,
                env=env,
                close_fds=True,
            )
        except OSError:
            # spawn failures (EAGAIN/ENOMEM) happen exactly when the
            # supervisor retry loop runs hot — leaking 3 fds per attempt
            # would march the parent to EMFILE
            parent_sock.close()
            child_sock.close()
            self._log.close()
            raise
        child_sock.close()
        procreg.register(self.proc.pid, f"bydb-worker {name}")
        self._router = threading.Thread(
            target=self._route, name=f"bydb-worker-router-{name}", daemon=True
        )
        self._router.start()

    # -- receive path -------------------------------------------------------
    def _route(self) -> None:
        try:
            while True:
                msg = _recv_frame(self._sock)
                if msg is None:
                    break
                if msg.get("ready"):
                    # the worker's persisted flush watermark (last
                    # journal seq applied before its newest durable
                    # flush): replay skips entries at or below it —
                    # they are already in parts on disk
                    self.flush_wm = int(msg.get("flush_wm", 0))
                    self._ready.set()
                    continue
                with self._pending_lock:
                    slot = self._pending.pop(msg.get("id"), None)
                if slot is not None:
                    slot["msg"] = msg
                    slot["evt"].set()
        except OSError:
            pass
        finally:
            self._dead.set()
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for slot in pending:
                slot["evt"].set()

    @property
    def alive(self) -> bool:
        return not self._dead.is_set() and self.proc.poll() is None

    def wait_ready(self, timeout: float = _SPAWN_TIMEOUT_S) -> None:
        if not self._ready.wait(timeout) or not self.alive:
            raise TransportError(
                f"worker {self.name} failed to start "
                f"(exit={self.proc.poll()}, log={self.root / 'worker.log'})"
            )

    # -- RPC ---------------------------------------------------------------
    def begin_call(
        self,
        topic: str,
        envelope: Optional[dict],
        *,
        env_json: Optional[str] = None,
    ) -> tuple:
        """Send the frame NOW (wire order = send order = the worker's
        ordered-thread apply order) and return a waiter handle for
        ``wait_reply`` — flush_worker sends under the journal lock but
        waits for the long-running reply outside it."""
        if not self.alive:
            raise TransportError(f"worker {self.name} down")
        mid = next(self._ids)
        slot: dict = {"evt": threading.Event(), "msg": None}
        with self._pending_lock:
            self._pending[mid] = slot
        if env_json is None:
            env_json = json.dumps(envelope)
        data = (
            '{"id": %d, "topic": %s, "env": %s}'
            % (mid, json.dumps(topic), env_json)
        ).encode()
        try:
            _send_frame(self._sock, self._send_lock, data=data)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(mid, None)
            self._dead.set()
            raise TransportError(f"worker {self.name} pipe closed: {e}") from e
        return mid, slot

    def wait_reply(self, handle: tuple, topic: str, timeout: float) -> dict:
        mid, slot = handle
        if not slot["evt"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(mid, None)
            # the call may still complete worker-side; classify like a
            # budget-clamped RPC timeout — the worker is not dead
            raise TransportError(
                f"worker {self.name} call {topic} timed out", kind="deadline"
            )
        msg = slot["msg"]
        if msg is None:
            raise TransportError(f"worker {self.name} died mid-call")
        if not msg.get("ok"):
            err = TransportError(
                msg.get("error", "worker error"), kind=msg.get("kind", "error")
            )
            err.remote = True  # the worker's HANDLER raised (vs. transport)
            raise err
        return msg["reply"]

    def call(
        self,
        topic: str,
        envelope: Optional[dict],
        timeout: float = 30.0,
        *,
        env_json: Optional[str] = None,
    ) -> dict:
        """``env_json`` is the envelope pre-serialized: the write plane
        journals the encoded form, so the hot path serializes ONCE (the
        frame splices it in verbatim) instead of dumps-for-size +
        dumps-for-wire."""
        handle = self.begin_call(topic, envelope, env_json=env_json)
        return self.wait_reply(handle, topic, timeout)

    # -- lifecycle ----------------------------------------------------------
    def kill(self) -> None:
        """SIGKILL (chaos harness; the supervisor restarts + replays)."""
        try:
            self.proc.kill()
        except OSError:
            pass

    def close(self, timeout: float = 10.0) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)
        self._dead.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._router.join(timeout=5)
        try:
            self._log.close()
        except OSError:
            pass
        procreg.unregister(self.proc.pid)


class WorkerTransport:
    """Liaison transport over the worker pipes: addr ``worker:<i>`` —
    a worker is one more scatter leg on the PR-7 envelope contract."""

    def __init__(self, pool: "WorkerPool"):
        self._pool = pool

    def call(
        self, addr: str, topic: str, envelope: dict, timeout: float = 30.0
    ) -> dict:
        faults.maybe_fail_rpc(addr, topic)
        assert addr.startswith("worker:"), addr
        client = self._pool._clients[int(addr.split(":", 1)[1])]
        if client is None:
            raise TransportError(f"worker {addr} restarting")
        return client.call(topic, envelope, timeout=timeout)


# -- parent side: the pool ----------------------------------------------------


class WorkerPool:
    """N shard-owning worker processes behind an embedded Liaison."""

    def __init__(
        self,
        root: str | Path,
        registry,
        n: int,
        *,
        query_budget_s: Optional[float] = None,
        journal_cap_mb: Optional[int] = None,
    ):
        from banyandb_tpu.obs.metrics import global_meter

        if n <= 0:
            raise ValueError("WorkerPool needs n >= 1 workers")
        self.root = Path(root) / "workers"
        self.registry = registry
        self.n = n
        self.meter = global_meter()
        self._names = [f"w{i:03d}" for i in range(n)]
        self._clients: list[Optional[WorkerClient]] = [None] * n
        self._jlocks = [threading.RLock() for _ in range(n)]
        self._journal: list[list] = [[] for _ in range(n)]
        self._jbytes = [0] * n
        self._seq = itertools.count(1)
        self._stopping = threading.Event()
        self.restarts = 0
        # workers whose registries may be behind the parent's (a schema
        # push failed while they were alive); the supervisor resyncs
        # them — restart-only catch-up would strand a live worker on
        # stale schema forever
        self._schema_stale: set[int] = set()
        self._stale_lock = threading.Lock()
        # parent-driven flush cadence: workers never drain memtables on
        # their own (worker_main passes local_flush=False), so the
        # supervisor flushes on the single-process loop's default
        # interval — same crash-loss window, journal trimmed in step
        from banyandb_tpu.utils.envflag import env_float

        self._flush_interval_s = max(
            env_float("BYDB_WORKER_FLUSH_S", 1.0), 0.05
        )
        # supervisor-thread-only; seeded with now so the first periodic
        # flush waits a full interval (monotonic() is not epoch-0-based)
        self._last_flush = [time.monotonic()] * n
        cap_mb = (
            journal_cap_mb
            if journal_cap_mb is not None
            else env_int("BYDB_WORKER_JOURNAL_MB", 64)
        )
        self._journal_cap = max(cap_mb, 1) * (1 << 20)
        # spawn the fleet concurrently (each pays the interpreter+jax
        # import once), then wait for every ready banner; any failure —
        # a Popen OSError mid-fleet included — reaps what already spawned
        clients: list[WorkerClient] = []
        try:
            for i in range(n):
                # journal seqs restart with THIS parent process: a
                # flush.wm persisted under a previous parent's seq
                # domain would wrongly skip this domain's replay
                try:
                    os.remove(self.root / self._names[i] / "flush.wm")
                except OSError:
                    pass
                clients.append(
                    WorkerClient(self._names[i], self.root / self._names[i])
                )
            for c in clients:
                c.wait_ready()
        except Exception:
            for c in clients:
                c.kill()
                c.close(timeout=2)
            raise
        self._clients = clients
        self.transport = WorkerTransport(self)
        nodes = [NodeInfo(self._names[i], f"worker:{i}") for i in range(n)]
        self.liaison = Liaison(
            registry,
            self.transport,
            nodes,
            replicas=0,
            query_budget_s=query_budget_s,
        )
        try:
            self._sync_schema_full()
            # future schema creates on the parent registry push through
            # the same plane the cluster liaison uses
            registry.watch(self._on_schema_put)
            for i in range(n):
                self._ctl(i, {"op": "streamagg-load"})
            self.liaison.probe()
        except Exception:
            # __init__ raising means the owner never gets a pool to
            # stop(): reap the fleet here or N workers (and their
            # procreg entries) outlive the failed construction
            self._stopping.set()
            for c in clients:
                c.kill()
                c.close(timeout=2)
            raise
        self._supervisor = threading.Thread(
            target=self._supervise, name="bydb-worker-supervisor", daemon=True
        )
        self._supervisor.start()

    # -- schema plane --------------------------------------------------------
    def _schema_objects(self):
        """(kind, obj) for every stored schema object, groups first
        (measures/streams/rules reference their group)."""
        store = self.registry._store
        kinds = ["group"] + [k for k in store if k != "group"]
        for kind in kinds:
            for obj in store.get(kind, {}).values():
                yield kind, obj

    def _sync_schema_full(self) -> None:
        for kind, obj in self._schema_objects():
            try:
                self.liaison.sync_schema(kind, obj)
            except TransportError:
                log.exception("initial schema sync failed for %s", kind)
                self._mark_schema_stale()

    def _sync_schema_to(self, widx: int, client: WorkerClient) -> None:
        from banyandb_tpu.api.schema import _to_jsonable

        for kind, obj in self._schema_objects():
            client.call(
                Topic.SCHEMA_SYNC.value,
                {"kind": kind, "item": _to_jsonable(obj)},
                timeout=_CTL_TIMEOUT_S,
            )

    def _mark_schema_stale(self) -> None:
        """A sync fan-out failed partway: liaison.sync_schema raises on
        the FIRST unreachable leg without reporting which workers it
        already reached, so every worker is suspect until the
        supervisor's (idempotent) full resync clears it."""
        with self._stale_lock:
            self._schema_stale.update(range(self.n))

    def _on_schema_put(self, kind: str, obj, revision: int) -> None:
        if self._stopping.is_set():
            return
        try:
            self.liaison.sync_schema(kind, obj)
        except Exception:  # noqa: BLE001 - never fail the local create:
            # a down worker catches up at restart via _sync_schema_to,
            # and a LIVE worker that missed the push (timeout, transient
            # pipe error) is resynced by the supervisor — without the
            # stale mark it would miss the schema until it crashed
            log.exception("schema push to workers failed for %s", kind)
            self._mark_schema_stale()

    # -- control -------------------------------------------------------------
    def _ctl(self, widx: int, env: dict, timeout: float = _CTL_TIMEOUT_S):
        client = self._clients[widx]
        if client is None:
            raise TransportError(f"worker {self._names[widx]} restarting")
        return client.call(CTL_TOPIC, env, timeout=timeout)

    # -- write plane ----------------------------------------------------------
    def _worker_of_shard(self, shard: int) -> int:
        # matches RoundRobinSelector placement over the zero-padded
        # name order (replicas=0): shard's primary is nodes[shard % n]
        return shard % self.n

    def _forward_write(self, widx: int, topic: str, env: dict) -> None:
        """Journal-then-forward (handoff-style ack): transport death
        keeps the entry for restart replay and still acks; a worker-side
        REJECTION (validation, shed) drops the entry and propagates —
        replaying it later would fail identically.

        The envelope is serialized exactly ONCE: the journal holds the
        encoded form (halves journal memory vs dict + re-dump), the wire
        frame splices it in verbatim, and replay re-sends the same
        bytes.  The journal seq is spliced into the encoded envelope as
        ``_seq`` (a string prepend, no re-serialization): the worker
        records the last seq it applied and persists it with each
        flush, so replay after a crash can skip entries whose rows are
        already in parts on disk — the at-least-once edge that would
        otherwise duplicate stream/trace appends (no version dedup)."""
        env_json = json.dumps(env)
        size = len(env_json)
        with self._jlocks[widx]:
            dead = (
                self._clients[widx] is None
                or not self._clients[widx].alive
            )
            if dead and self._jbytes[widx] + size > self._journal_cap:
                # the pressure valve for a dead worker: nothing can trim
                # the spool (journal-pressure flush needs a live worker),
                # so past the cap the write SHEDS — a retryable
                # ServerBusy (kind="shed" on the wire, the wqueue
                # high-watermark contract) instead of acking into
                # unbounded parent memory
                from banyandb_tpu.admin.protector import ServerBusy

                self.meter.counter_add(
                    "worker_journal_shed", 1.0,
                    {"worker": self._names[widx]},
                )
                raise ServerBusy(
                    f"worker {self._names[widx]} down and its write "
                    f"journal is full ({self._jbytes[widx]} bytes >= "
                    f"{self._journal_cap}); retry after restart"
                )
            seq = next(self._seq)
            # write envelopes are never the empty object, so the splice
            # below always yields valid JSON
            env_json = '{"_seq": %d, %s' % (seq, env_json[1:])
            size = len(env_json)
            self._journal[widx].append((seq, topic, env_json, size))
            # bdlint: disable=wp-shared-state -- every write to the
            # journal fields happens under self._jlocks[widx] (a
            # per-worker lock held by THIS with-block and by
            # flush_worker/_restart); the analyzer's lockset model
            # tracks attribute locks, not per-index list elements
            self._jbytes[widx] += size
            client = self._clients[widx]
            if client is None or not client.alive:
                return  # spooled ack: replay delivers after restart
            # SEND under the lock (the frame must hit the worker's
            # ordered thread in journal-seq order), but wait for the
            # reply OUTSIDE it — same split as flush_worker — so one
            # slow apply doesn't serialize every writer thread and the
            # flush loop behind a worker-long lock hold.
            try:
                handle = client.begin_call(topic, None, env_json=env_json)
            except TransportError:
                # pipe died at send: journaled + acked (spooled ack);
                # restart replay delivers
                return
        try:
            client.wait_reply(handle, topic, _WRITE_TIMEOUT_S)
        except TransportError as e:
            if getattr(e, "remote", False):
                with self._jlocks[widx]:
                    # remove by seq — concurrent writes may have
                    # journaled behind this entry while we waited
                    j = self._journal[widx]
                    for k in range(len(j) - 1, -1, -1):
                        if j[k][0] == seq:
                            del j[k]
                            self._jbytes[widx] -= size
                            break
                raise
            # died/timed out mid-call: journaled + acked; an
            # applied-but-unacked duplicate collapses in the
            # (series, ts, version) dedup on replay

    def write_measure(self, req) -> int:
        """Row-shaped measure write partitioned by the existing shard
        hash; returns the accepted point count (the 0-mode contract)."""
        from banyandb_tpu.api.model import WriteRequest

        m = self.registry.get_measure(req.group, req.name)
        shard_num = self.registry.get_group(req.group).resource_opts.shard_num
        buckets: dict[int, list] = {}
        for p in req.points:
            entity = [req.name.encode()] + [
                hashing.entity_bytes(p.tags[t]) for t in m.entity.tag_names
            ]
            shard = hashing.shard_id(hashing.series_id(entity), shard_num)
            buckets.setdefault(self._worker_of_shard(shard), []).append(p)
        for widx, pts in sorted(buckets.items()):
            env = {
                "request": serde.write_request_to_json(
                    WriteRequest(req.group, req.name, tuple(pts))
                )
            }
            self._forward_write(widx, Topic.MEASURE_WRITE.value, env)
        return len(req.points)

    def write_measure_columns(self, env: dict) -> int:
        """Columnar envelope: decode once, route rows by vectorized
        entity hashing (the engine's own series_ids_for_columns), and
        forward per-worker slices re-encoded with the same codec."""
        import numpy as np

        from banyandb_tpu.models.measure import (
            DictColumn,
            series_ids_for_columns,
        )

        cols = serde.write_columns_env_decode(env)
        group, name = cols["group"], cols["name"]
        m = self.registry.get_measure(group, name)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        n = int(cols["ts_millis"].size)
        if n == 0:
            return 0
        # 0-mode parity on the ERROR path: the engine's write_columns
        # validates every column before touching a memtable, but a
        # worker may be down at forward time (journal-spooled ack), so
        # the worker's validation can run AFTER this call returned
        # written=n — a ragged non-entity column would be acked, then
        # deterministically rejected at replay and silently lost.
        # Validate the full envelope here, before anything is acked.
        for t in m.tags:
            col = cols["tags"].get(t.name)
            if col is None:
                continue
            if isinstance(col, DictColumn):
                codes = np.asarray(col.codes)
                if len(codes) != n:
                    raise ValueError(
                        f"tag {t.name}: {len(codes)} codes for {n} rows"
                    )
                if codes.size and (
                    int(codes.min()) < 0
                    or int(codes.max()) >= len(col.values)
                ):
                    raise ValueError(
                        f"tag {t.name}: code out of range for dict of "
                        f"{len(col.values)}"
                    )
            elif len(col) != n:
                raise ValueError(
                    f"tag {t.name}: {len(col)} values for {n} rows"
                )
        for f in m.fields:
            fcol = cols["fields"].get(f.name)
            if fcol is not None and len(fcol) != n:
                raise ValueError(
                    f"field {f.name}: {len(fcol)} values for {n} rows"
                )
        if cols.get("versions") is not None and len(cols["versions"]) != n:
            raise ValueError(f"{len(cols['versions'])} versions for {n} rows")
        ent_cols = []
        for t in m.entity.tag_names:
            col = cols["tags"].get(t)
            if col is None:
                raise KeyError(t)
            if isinstance(col, DictColumn):
                codes = np.asarray(col.codes)
                ent_cols.append(
                    DictColumn(
                        [
                            hashing.entity_bytes(v) if v is not None else b""
                            for v in col.values
                        ],
                        codes,
                    )
                )
            else:
                ent_cols.append(
                    [
                        hashing.entity_bytes(v) if v is not None else b""
                        for v in col
                    ]
                )
        sids, _ = series_ids_for_columns(name, ent_cols, n)
        widx = (sids % shard_num) % self.n
        for w in np.unique(widx).tolist():
            idx = np.nonzero(widx == w)[0]
            sub = (
                env
                if len(idx) == n
                else serde.write_columns_env_slice(cols, idx)
            )
            self._forward_write(int(w), Topic.MEASURE_WRITE_COLUMNS.value, sub)
        return n

    def write_stream(self, group: str, name: str, elements: list[dict]) -> int:
        """Same shard routing + envelope the liaison's write_stream
        would send, but through the parent journal: the crash contract
        ('worker death loses nothing acked') covers every model, so
        stream writes spool/replay exactly like measure writes."""
        from banyandb_tpu.api.schema import _to_jsonable

        schema = _to_jsonable(self.registry.get_stream(group, name))
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        entity_tags = schema["entity"]
        buckets: dict[int, list] = {}
        for e in elements:
            entity = [name.encode()] + [
                hashing.entity_bytes(e["tags"][t]) for t in entity_tags
            ]
            shard = hashing.shard_id(hashing.series_id(entity), shard_num)
            buckets.setdefault(self._worker_of_shard(shard), []).append(e)
        for widx, elems in sorted(buckets.items()):
            env = {
                "group": group, "name": name,
                "schema": schema, "elements": elems,
            }
            self._forward_write(widx, Topic.STREAM_WRITE.value, env)
        return len(elements)

    def write_trace(
        self, group: str, name: str, spans: list[dict], ordered_tags=()
    ) -> int:
        """Trace twin of write_stream: journaled-then-forwarded."""
        from banyandb_tpu.api.schema import _to_jsonable
        from banyandb_tpu.models.trace import trace_shard_id

        schema = _to_jsonable(self.registry.get_trace(group, name))
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tid_tag = schema["trace_id_tag"]
        buckets: dict[int, list] = {}
        for s in spans:
            shard = trace_shard_id(str(s["tags"][tid_tag]), shard_num)
            buckets.setdefault(self._worker_of_shard(shard), []).append(s)
        for widx, batch in sorted(buckets.items()):
            env = {
                "group": group, "name": name, "schema": schema,
                "spans": batch, "ordered_tags": list(ordered_tags),
            }
            self._forward_write(widx, Topic.TRACE_WRITE.value, env)
        return len(spans)

    # -- query plane ----------------------------------------------------------
    def query_measure(self, req, tracer=None):
        return self.liaison.query_measure(req, tracer=tracer)

    def query_stream(self, req, tracer=None):
        return self.liaison.query_stream(req, tracer=tracer)

    def query_trace_by_id(self, group: str, name: str, trace_id: str):
        return self.liaison.query_trace_by_id(group, name, trace_id)

    def query_trace_ordered(self, *a, **kw):
        return self.liaison.query_trace_ordered(*a, **kw)

    def query_trace(self, req, tracer=None):
        return self.liaison.query_trace(req, tracer=tracer)

    def topn(self, env: dict) -> dict:
        """Scatter the node-local TopN ranking to every worker and
        re-rank the union — entities are shard-routed, so per-worker
        entity sets are disjoint and concat is exact.  A down worker (or
        a leg lost to a transport failure) degrades the answer, so the
        reply carries the measure/stream ``degraded``/``unavailable_nodes``
        markers instead of posing as complete."""
        # agg="count" flattens every ranked item to 1.0 AFTER the
        # truncation (query_topn's distinct-best contract) — workers
        # must therefore rank on the underlying distinct-best value
        # (any non-count agg equals it) or the parent re-rank would
        # sort a sea of 1.0s by entity and pick a different top-n set
        # than BYDB_WORKERS=0
        agg = env.get("agg", "sum")
        wenv = dict(env, agg="sum") if agg == "count" else env
        items: list[dict] = []
        unavailable: list[str] = []
        for i in range(self.n):
            client = self._clients[i]
            if client is None or not client.alive:
                unavailable.append(self._names[i])
                continue  # degraded TopN over surviving workers
            try:
                items.extend(client.call("topn", wenv, timeout=30.0)["items"])
            except TransportError as e:
                if getattr(e, "remote", False):
                    raise  # e.g. unknown rule: 0-mode parity
                unavailable.append(self._names[i])
        desc = env.get("direction", "desc") != "asc"
        # (value, entity) key matches models/topn.py query_topn's
        # tie-break, so equal values rank identically vs BYDB_WORKERS=0
        items.sort(
            key=lambda it: (it["value"], tuple(it["entity"])), reverse=desc
        )
        items = items[: env.get("n", 10)]
        if agg == "count":
            items = [{"entity": it["entity"], "value": 1.0} for it in items]
        out: dict = {"items": items}
        if unavailable:
            out["degraded"] = True
            out["unavailable_nodes"] = sorted(unavailable)
        return out

    def streamagg(self, env: dict) -> dict:
        op = env.get("op", "stats")
        if op == "register":
            acks = self.liaison.register_streamagg(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
                max_windows=env.get("max_windows"),
                origin=env.get("origin", "manual"),
            )
            return {"registered": acks}
        if op == "unregister":
            acks = self.liaison.unregister_streamagg(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
            )
            return {
                "unregistered": any(
                    a.get("unregistered") for a in acks.values()
                ),
                "acks": acks,
            }
        if op == "stats":
            out = {}
            for i in range(self.n):
                client = self._clients[i]
                if client is None or not client.alive:
                    continue
                try:
                    out[self._names[i]] = client.call(
                        "streamagg", {"op": "stats"}, timeout=30.0
                    ).get("streamagg")
                except TransportError as e:
                    if getattr(e, "remote", False):
                        raise
                    # died between the alive check and the call: skip,
                    # like topn()/metrics_text() — stats stay degradable
            return {"streamagg": out}
        raise ValueError(f"bad streamagg op {op!r}")

    # -- flush / journal trim -------------------------------------------------
    def flush_worker(self, widx: int, group: Optional[str] = None) -> list:
        """Flush one worker's memtables and trim its journal to the
        watermark the WORKER reports back (the last journal seq it had
        applied when the flush drained its memtables — every row at or
        below it is now in parts on disk, durably marked by the
        worker's flush.wm file).  A group-scoped flush reports no
        watermark (other groups' memtables still hold journaled rows)
        and trims nothing.

        The flush frame is SENT under the journal lock — it must order
        after every delivered write on the worker's ordered thread —
        but the reply wait happens OUTSIDE it: a flush can run for
        seconds and must not stall ingest to this worker's shards.
        Writes that land while the flush runs apply after it, get
        seq > wm, and are untouched by the trim."""
        with self._jlocks[widx]:
            client = self._clients[widx]
            if client is None or not client.alive:
                return []
            handle = client.begin_call(
                CTL_TOPIC, {"op": "flush", "group": group}
            )
        r = client.wait_reply(handle, CTL_TOPIC, _CTL_TIMEOUT_S)
        wm = r.get("flush_wm")
        if wm is None:
            return r.get("parts", [])
        with self._jlocks[widx]:
            if self._clients[widx] is not client:
                # the worker restarted while we waited: replay already
                # re-delivered the journal; a stale watermark must not
                # trim entries the fresh incarnation still needs
                return r.get("parts", [])
            j = self._journal[widx]
            keep = [e for e in j if e[0] > wm]
            self._jbytes[widx] -= sum(e[3] for e in j) - sum(
                e[3] for e in keep
            )
            # bdlint: disable=wp-shared-state -- guarded by
            # self._jlocks[widx] (held by this with-block), same
            # per-worker-lock invariant as _jbytes
            self._journal[widx] = keep
            return r.get("parts", [])

    def flush(self, group: Optional[str] = None) -> list:
        out: list = []
        for i in range(self.n):
            try:
                out.extend(self.flush_worker(i, group))
            except TransportError:
                log.exception("flush of worker %s failed", self._names[i])
        return out

    # -- obs ------------------------------------------------------------------
    def metrics_text(self) -> str:
        """Worker expositions merged with per-worker labels (the
        scatter:<node> graft idea applied to /metrics)."""
        parts = []
        for i in range(self.n):
            client = self._clients[i]
            if client is None or not client.alive:
                continue
            try:
                text = client.call("metrics", {}, timeout=10.0)["prometheus"]
            except TransportError:
                continue
            parts.append(
                relabel_exposition(text, {"worker": self._names[i]})
            )
        return "\n".join(p for p in parts if p)

    def stats(self) -> dict:
        return {
            "workers": self.n,
            "alive": sorted(self.liaison.alive),
            "restarts": self.restarts,
            "journal_bytes": list(self._jbytes),
            "journal_entries": [len(j) for j in self._journal],
        }

    # -- crash supervision ----------------------------------------------------
    def kill_worker(self, widx: int) -> int:
        """SIGKILL one worker (chaos harness).  Returns its pid; the
        supervisor restarts it and replays the journal."""
        client = self._clients[widx]
        if client is None:
            raise RuntimeError(f"worker {widx} already restarting")
        pid = client.proc.pid
        client.kill()
        return pid

    def _replay_locked(self, widx: int, client: WorkerClient) -> int:
        replayed = 0
        kept = []
        for entry in self._journal[widx]:
            seq, topic, env_json, size = entry
            if seq <= client.flush_wm:
                # the dead incarnation flushed this entry into parts
                # (its persisted flush.wm proves it) but died before
                # the parent's trim: re-sending would append
                # stream/trace rows a second time
                self._jbytes[widx] -= size
                continue
            try:
                client.call(
                    topic, None, timeout=_WRITE_TIMEOUT_S, env_json=env_json
                )
                kept.append(entry)
                replayed += 1
            except TransportError as e:
                if (
                    getattr(e, "remote", False)
                    and getattr(e, "kind", "error") == "error"
                ):
                    # a DETERMINISTIC rejection (validation): it would
                    # have failed live too — drop, never wedge the
                    # replay.  Shed/deadline kinds are transient
                    # (DiskFull/ServerBusy from a healthy worker): the
                    # entry was ACKED, so it must survive for the
                    # supervisor's next restart+replay attempt.
                    log.warning(
                        "replay drop on %s: %s", self._names[widx], e
                    )
                    self._jbytes[widx] -= size
                    continue
                # died again mid-replay, or a transient shed: keep THIS
                # and all later entries for the next attempt
                kept.extend(
                    x for x in self._journal[widx] if x[0] >= seq
                )
                self._journal[widx] = kept
                raise
        self._journal[widx] = kept
        return replayed

    def _restart(self, widx: int) -> None:
        name = self._names[widx]
        with self._jlocks[widx]:
            old, self._clients[widx] = self._clients[widx], None
        if old is not None:
            old.close(timeout=5)
        if self._stopping.is_set():
            return  # shutdown raced the crash: reap only, never respawn
        self.restarts += 1
        self.meter.counter_add("worker_restarts", 1.0, {"worker": name})
        log.warning("worker %s died; restarting (replay from journal)", name)
        client = WorkerClient(name, self.root / name)
        try:
            client.wait_ready()
            self._sync_schema_to(widx, client)
            with self._stale_lock:
                self._schema_stale.discard(widx)
            with self._jlocks[widx]:
                self._replay_locked(widx, client)
                self._clients[widx] = client
            # streamagg AFTER replay: the backfill snapshot now holds
            # surviving parts + replayed memtable rows in one dedup pass
            client.call(CTL_TOPIC, {"op": "streamagg-load"}, timeout=_CTL_TIMEOUT_S)
        except TransportError:
            client.kill()
            client.close(timeout=2)
            raise
        self.liaison.forget_streamagg_sent(name)
        self.liaison.probe()

    def _supervise(self) -> None:
        while not self._stopping.wait(0.25):
            needs_probe = False
            for i in range(self.n):
                if self._stopping.is_set():
                    return
                client = self._clients[i]
                # a None slot means a previous restart attempt failed
                # mid-flight (spawn/schema-sync/replay raised after the
                # slot was cleared) — it must keep retrying, or the
                # worker stays down for the process lifetime
                if client is None or not client.alive:
                    try:
                        self._restart(i)
                    except Exception:  # noqa: BLE001 - retry next tick
                        log.exception(
                            "worker %s restart failed", self._names[i]
                        )
                        time.sleep(0.5)
                    continue
                # schema reconcile: a live worker that missed a push
                # gets the full (idempotent) object set again
                with self._stale_lock:
                    stale = i in self._schema_stale
                if stale:
                    try:
                        self._sync_schema_to(i, client)
                        with self._stale_lock:
                            self._schema_stale.discard(i)
                    except TransportError:
                        log.exception(
                            "schema resync to %s failed", self._names[i]
                        )
                # liveness reconcile: one errored scatter leg evicts a
                # worker from liaison.alive, but only probe() readmits
                # it — without this, a healthy worker whose handler once
                # raised degrades every later query until it crashes
                if self._names[i] not in self.liaison.alive:
                    needs_probe = True
                now = time.monotonic()
                if self._jbytes[i] > self._journal_cap or (
                    self._journal[i]
                    and now - self._last_flush[i] >= self._flush_interval_s
                ):
                    # workers never drain memtables themselves
                    # (local_flush=False): this tick is THE flush loop
                    # for worker shards, and the only journal trim
                    self._last_flush[i] = now
                    try:
                        self.flush_worker(i)
                    except TransportError:
                        log.exception(
                            "parent-driven flush of %s failed",
                            self._names[i],
                        )
            if needs_probe:
                self.liaison.probe()

    def stop(self) -> None:
        self._stopping.set()
        # a restart in flight holds the supervisor (spawn + schema sync
        # + replay can exceed a short join); wait it out — leaking the
        # supervisor thread would fail the bdsan thread-parity check
        self._supervisor.join(timeout=_SPAWN_TIMEOUT_S)
        for i in range(self.n):
            client = self._clients[i]
            if client is None:
                continue
            try:
                if client.alive:
                    client.call(CTL_TOPIC, {"op": "stop"}, timeout=30.0)
            except TransportError:
                pass
            client.close()
            self._clients[i] = None


# -- engine-shaped adapters (WireServices / TopN / self-measure reuse) --------


class PoolMeasureAdapter:
    """Engine-shaped facade over the pool's distributed measure plane
    (the _LiaisonMeasureAdapter idea, intra-node edition): TopN
    post-processing and the self-measure sink run against the pool
    without knowing about processes."""

    def __init__(self, pool: WorkerPool):
        self._pool = pool
        self.registry = pool.registry

    def query(self, req, shard_ids=None, tracer=None):
        return self._pool.query_measure(req, tracer=tracer)

    def write(self, req, _internal: bool = False) -> int:
        return self._pool.write_measure(req)

    def write_points_bulk(self, req) -> int:
        return self._pool.write_measure(req)

    def flush(self, group=None) -> list:
        return self._pool.flush(group)

    def topn_scatter(self, env: dict) -> dict:
        """The wire's TopN entry in worker mode: result-measure rows
        live worker-locally in arbitrary shards (each worker's TopN
        manager writes its own winners), so a shard-routed
        query_measure would silently miss rows — the pool's concat
        re-rank over the per-worker ranked lists is the exact plane."""
        return self._pool.topn(env)


class PoolStreamAdapter:
    """Stream twin of PoolMeasureAdapter: queries scatter through the
    embedded liaison, writes journal-then-forward through the pool —
    the wire surface's acks get the same crash contract as bus writes."""

    def __init__(self, pool: WorkerPool):
        self._pool = pool

    def query(self, req, shard_ids=None):
        return self._pool.query_stream(req)

    def write(self, group: str, name: str, elements) -> int:
        import base64

        return self._pool.write_stream(
            group, name,
            [
                {
                    "element_id": e.element_id,
                    "ts": e.ts_millis,
                    "tags": e.tags,
                    "body": base64.b64encode(e.body).decode(),
                }
                for e in elements
            ],
        )


class PoolTraceAdapter:
    """Trace-engine facade for ql_exec.execute_trace_ql over workers.
    Writes journal through the pool like every other model."""

    def __init__(self, pool: WorkerPool):
        self._pool = pool

    def get_trace(self, group: str, name: str):
        return self._pool.registry.get_trace(group, name)

    def query_by_trace_id(self, group: str, name: str, trace_id: str):
        return self._pool.query_trace_by_id(group, name, trace_id)

    def query_ordered(self, group, name, order_tag, time_range, **kw):
        kw.pop("with_keys", None)
        return self._pool.query_trace_ordered(
            group, name, order_tag, time_range, **kw
        )

    def query(self, req, *, shard_ids=None, tracer=None):
        return self._pool.query_trace(req, tracer=tracer)

    def write(self, group: str, name: str, spans, *, ordered_tags=()) -> int:
        import base64

        return self._pool.write_trace(
            group, name,
            [
                {
                    "ts": s.ts_millis,
                    "tags": s.tags,
                    "span": base64.b64encode(s.span).decode(),
                }
                for s in spans
            ],
            ordered_tags=tuple(ordered_tags),
        )


# -- exposition relabeling ----------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?( .+)$"
)


def relabel_exposition(text: str, extra: dict) -> str:
    """Inject labels into every sample line of a Prometheus exposition
    (comment lines dropped — the merged text is for scrapers, which
    aggregate across the injected label)."""
    inject = ",".join(f'{k}="{v}"' for k, v in sorted(extra.items()))
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, _, labels, rest = m.groups()
        merged = f"{labels},{inject}" if labels else inject
        out.append(f"{name}{{{merged}}}{rest}")
    return "\n".join(out)


# -- worker side --------------------------------------------------------------


def _read_wm(path: Optional[Path]) -> int:
    if path is None:
        return 0
    try:
        return int(path.read_text().strip() or 0)
    except (OSError, ValueError):
        return 0


def _write_wm(path: Optional[Path], seq: int) -> None:
    """Persist the flush watermark atomically (tmp + rename): a crash
    mid-write must leave the OLD watermark, never a torn one — replay
    over-delivery is collapsible for measures and bounded for
    streams/traces only because the watermark is trustworthy."""
    if path is None:
        return
    # disk-fault boundary: ENOSPC raises before the tmp write, so the
    # rename never runs and the OLD watermark stays authoritative
    faults.check_disk("worker-watermark")
    tmp = path.with_suffix(".tmp")
    tmp.write_text(str(seq))
    os.replace(tmp, path)


class _WorkerServer:
    """Serve a DataNode's bus over the parent socketpair: ordered
    topics on ONE writer thread (journal-seq apply order), the rest on
    a small executor."""

    def __init__(self, sock: socket.socket, node, wm_path: Optional[Path] = None):
        import queue
        from concurrent import futures

        self.sock = sock
        self.node = node
        self.wm_path = wm_path
        # last parent-journal seq applied on the writer thread; the
        # flush ctl op persists it NEXT TO the parts it drained, so a
        # restart replays only entries the durable state lacks.  Written
        # and read on the writer thread alone (ctl is an ordered topic).
        self.applied_seq = _read_wm(wm_path)
        self._send_lock = threading.Lock()
        self._stop = threading.Event()
        self._writeq: "queue.Queue" = queue.Queue()
        self._pool = futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="bydb-worker-rpc"
        )
        self._writer = threading.Thread(
            target=self._write_loop, name="bydb-worker-writer", daemon=True
        )

    def request_stop(self) -> None:
        self._stop.set()
        try:
            # unblocks the main recv loop; replies still flush out
            self.sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass

    def _reply(self, mid, payload: dict) -> None:
        try:
            _send_frame(self.sock, self._send_lock, dict(payload, id=mid))
        except OSError:
            self._stop.set()

    def _handle(self, msg: dict) -> None:
        try:
            env = msg.get("env") or {}
            reply = self.node.bus.handle(msg["topic"], env)
            if msg["topic"] in ORDERED_TOPICS and "_seq" in env:
                # bdlint: disable=wp-shared-state -- the ORDERED_TOPICS
                # guard makes this branch writer-thread-only (serve()
                # routes every ordered topic to the single writer
                # thread; the executor never sees one), so applied_seq
                # is single-writer and read on the same thread by the
                # ctl flush handler
                self.applied_seq = env["_seq"]
            self._reply(msg["id"], {"ok": True, "reply": reply})
        except Exception as e:  # noqa: BLE001 - errors cross the pipe
            self._reply(
                msg["id"],
                {
                    "ok": False,
                    "kind": _error_kind(e),
                    "error": f"{type(e).__name__}: {e}",
                },
            )

    def _write_loop(self) -> None:
        while True:
            msg = self._writeq.get()
            if msg is None:
                return
            self._handle(msg)

    def serve(self) -> None:
        self._writer.start()
        _send_frame(
            self.sock,
            self._send_lock,
            {"ready": True, "pid": os.getpid(), "flush_wm": self.applied_seq},
        )
        try:
            while not self._stop.is_set():
                msg = _recv_frame(self.sock)
                if msg is None:
                    break
                if msg.get("topic") in ORDERED_TOPICS:
                    self._writeq.put(msg)
                else:
                    self._pool.submit(self._handle, msg)
        finally:
            self._writeq.put(None)
            self._writer.join(timeout=10)
            self._pool.shutdown(wait=True)


def _ctl_handler(node, server: _WorkerServer, env: dict) -> dict:
    op = env.get("op", "ping")
    if op == "ping":
        return {"pong": True, "pid": os.getpid()}
    if op == "flush":
        # runs ON the writer thread (CTL_TOPIC is ordered): every write
        # received before this frame is applied, so the parent's
        # last-forwarded seq is a sound journal trim watermark
        # pending TopN windows emit into the result measure first (the
        # emissions are ordinary versioned writes; later data re-emits
        # with a higher version), so they reach the flushed parts
        node.measure.topn.flush_all_windows()
        parts = list(node.measure.flush(env.get("group")))
        parts += node.stream.flush(env.get("group"))
        parts += node.trace.flush(env.get("group"))
        # group-scoped flushes leave other groups' memtables undrained:
        # rows <= applied_seq may then exist ONLY in the journal, so the
        # watermark (and the trim it licenses) must not advance
        if env.get("group") is None:
            _write_wm(server.wm_path, server.applied_seq)
            return {"parts": parts, "flush_wm": server.applied_seq}
        return {"parts": parts}
    if op == "streamagg-load":
        return {"loaded": node.measure.streamagg.load_persisted()}
    if op == "stop":
        server.request_stop()
        return {"stopping": True}
    raise ValueError(f"bad worker-ctl op {op!r}")


def worker_main(argv=None) -> int:
    """Worker process entry (``python -m banyandb_tpu.cluster.workers``):
    a DataNode over its own root, served over the parent socketpair.
    This function is a PROCESS root: everything it reaches runs outside
    the parent's thread population (wp-shared-state models it as a
    thread root)."""
    import argparse

    ap = argparse.ArgumentParser("bydb shard worker")
    ap.add_argument("--fd", type=int, required=True)
    ap.add_argument("--root", required=True)
    ap.add_argument("--name", required=True)
    args = ap.parse_args(argv)

    from banyandb_tpu.api.schema import SchemaRegistry
    from banyandb_tpu.cluster.data_node import DataNode
    from banyandb_tpu.utils import compile_cache

    sock = socket.socket(  # bdlint: disable=resource-hygiene -- the
        # worker's lifetime handle to its parent; closed in the
        # serve() finally below
        fileno=args.fd
    )
    root = Path(args.root)
    # workers share the pool's persistent XLA compile cache (the parent
    # stamps BYDB_COMPILE_CACHE_DIR into the child env): plan kernels
    # compile once per machine, not once per worker process
    compile_cache.enable()
    registry = SchemaRegistry(root)
    node = DataNode(args.name, registry, root / "data")
    server = _WorkerServer(sock, node, wm_path=root / "flush.wm")
    node.bus.subscribe(CTL_TOPIC, lambda env: _ctl_handler(node, server, env))
    # local_flush=False: memtables drain ONLY through the parent's ctl
    # flush (the journal-trim watermark path).  A loop-driven drain here
    # would persist journaled rows the parent never trimmed — after a
    # SIGKILL the replay would then append stream/trace elements a
    # second time (no version dedup in those models).  Merge/retention/
    # rotation/blooms/index-persist keep their normal cadence.
    node.start_lifecycle(local_flush=False)
    try:
        server.serve()
    finally:
        try:
            node.stop_lifecycle()
            node.measure.close()
            node.stream.close()
            node.trace.close()
        except Exception:  # noqa: BLE001 - exit anyway; parent owns the
            # durability story (journal + parts already on disk)
            log.exception("worker %s teardown failed", args.name)
        try:
            sock.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
