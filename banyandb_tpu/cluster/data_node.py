"""Data node role: engines + bus handlers (pkg/cmdsetup/data.go analog).

Hosts the storage engines and serves the internal topics: writes land in
the local engines; partial-aggregate queries run the device map phase
over the shard subset named in the envelope; chunked part sync
reassembles shipped parts.
"""

from __future__ import annotations

import zlib
from pathlib import Path

from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster import serde
from banyandb_tpu.cluster.bus import LocalBus, Topic
from banyandb_tpu.models.measure import MeasureEngine
from banyandb_tpu.utils import fs


class DataNode:
    def __init__(self, name: str, registry: SchemaRegistry, root: str | Path):
        import shutil

        from banyandb_tpu.models.stream import StreamEngine
        from banyandb_tpu.models.trace import TraceEngine

        self.name = name
        self.registry = registry
        self.root = Path(root)
        # advisory owner record: offline tools (lifecycle CLI) refuse to
        # open a root whose recorded owner process is still alive —
        # two Shard owners over one directory lose writes
        self.root.mkdir(parents=True, exist_ok=True)
        try:
            import os as _os

            (self.root / ".bydb-node.pid").write_text(str(_os.getpid()))
        except OSError:
            pass
        self.measure = MeasureEngine(registry, self.root)
        self.stream = StreamEngine(registry, self.root)
        self.trace = TraceEngine(registry, self.root)
        self.bus = LocalBus()
        from banyandb_tpu.admin.diskmonitor import DiskMonitor

        self.disk = DiskMonitor(self.root)
        # Persisted content digests of installed synced parts, for
        # idempotent re-delivery.  dict-as-ordered-set so the size bound
        # evicts the OLDEST digest, never a fresh one.
        import json as _json
        import threading

        try:
            self._installed = dict.fromkeys(
                _json.loads((self.root / ".sync-installed.json").read_text())
            )
        except (OSError, ValueError):
            self._installed = {}
        self._installed_lock = threading.Lock()
        # placement-epoch write fence (cluster/placement.py): the
        # highest epoch this node has seen, persisted so a restart
        # keeps rejecting writers from before the last witnessed
        # cutover (docs/robustness.md "Elastic cluster")
        from banyandb_tpu.cluster.placement import EpochRecord

        self.epoch_record = EpochRecord(self.root / ".placement-epoch.json")
        # content-digest cache for rebalance/repair manifests (parts
        # are immutable, so a digest computed once is good forever)
        self._manifest_digests: dict[str, str] = {}
        self._manifest_lock = threading.Lock()
        self._sync_sessions: dict[str, dict] = {}
        # abandoned chunked-sync sessions from a previous process die here
        shutil.rmtree(self.root / ".sync-staging", ignore_errors=True)
        self._register_handlers()

    def start_lifecycle(self, local_flush: bool = True, **kw) -> None:
        """Background flush/merge/retention over ALL engines' TSDBs —
        installed stream/measure parts (liaison wqueue, tier sync) merge
        and retention-sweep like locally-written ones; the extra tick
        runs trace maintenance (blooms + sidx flush/merge).

        local_flush=False keeps every maintenance tick (merge sweep,
        retention, rotation, blooms, series-index persist — all
        idempotent over immutable parts) but never drains memtables or
        sidx ordered keys: parts then publish ONLY through explicit
        engine flushes.  Worker processes need this — their parent trims
        its replay journal on the flushes IT initiates, so a loop-driven
        drain here would persist journaled rows the parent still replays
        after a crash, duplicating stream/trace appends (measure rows
        collapse in version dedup; streams/traces have none)."""
        if not local_flush:
            # no shard grows a memtable this large: the flush stage
            # visits every tick but never drains
            kw.setdefault("flush_min_rows", 1 << 62)
        self.measure.start_lifecycle(
            extra_tsdbs=lambda: (
                list(self.stream._tsdbs.values())
                + list(self.trace._tsdbs.values())
            ),
            extra_tick=lambda: self.trace.maintain(flush_sidx=False),
            pre_flush=self.trace._flush_sidx_first if local_flush else None,
            **kw,
        )

    def stop_lifecycle(self) -> None:
        self.measure.stop_lifecycle()

    def _register_handlers(self) -> None:
        self.bus.subscribe(Topic.MEASURE_WRITE, self._on_measure_write)
        self.bus.subscribe(
            Topic.MEASURE_WRITE_COLUMNS, self._on_measure_write_columns
        )
        self.bus.subscribe(Topic.MEASURE_QUERY_PARTIAL, self._on_measure_query_partial)
        self.bus.subscribe(Topic.MEASURE_QUERY_RAW, self._on_measure_query_raw)
        self.bus.subscribe(Topic.STREAM_WRITE, self._on_stream_write)
        self.bus.subscribe(Topic.STREAM_QUERY, self._on_stream_query)
        self.bus.subscribe(Topic.TRACE_WRITE, self._on_trace_write)
        self.bus.subscribe(Topic.TRACE_QUERY_BY_ID, self._on_trace_query)
        self.bus.subscribe(Topic.TRACE_QUERY_ORDERED, self._on_trace_query_ordered)
        self.bus.subscribe(Topic.TRACE_QUERY_EXEC, self._on_trace_query_exec)
        self.bus.subscribe(
            Topic.HEALTH,
            lambda env: {
                "status": "ok",
                "node": self.name,
                "schema_revision": self.registry.revision,
            },
        )
        self.bus.subscribe(Topic.SCHEMA_SYNC, self._on_schema_sync)
        self.bus.subscribe(
            Topic.SCHEMA_GET,
            lambda env: self.registry.stored_object_hash(
                env["kind"], env["key"]
            ),
        )
        self.bus.subscribe(Topic.SYNC_PART, self._on_sync_part)
        # node-local metrics exposition ("metrics" topic, same envelope
        # as the standalone server's TOPIC_METRICS): stage histograms
        # and engine instruments land in the process-global meter
        from banyandb_tpu.obs import metrics as obs_metrics

        self.bus.subscribe(
            "metrics",
            lambda env: {
                "prometheus": obs_metrics.global_meter().prometheus_text()
            },
        )
        # streaming-aggregation control surface (query/streamagg.py):
        # liaisons broadcast dashboard signature registrations here;
        # stats expose window/watermark state per node
        self.bus.subscribe("streamagg", self._on_streamagg)
        # elastic-cluster control surface (docs/robustness.md):
        # placement-epoch get/adopt + the rebalance/repair data plane
        # (per-shard part manifests, chunked part pulls, all-model
        # flush before a manifest snapshot)
        self.bus.subscribe("placement", self._on_placement)
        self.bus.subscribe("rebalance", self._on_rebalance)
        # node-local TopN ranking over pre-aggregated windows — scatter
        # callers (the worker pool, a future liaison TopN plane) merge
        # per-node ranked lists
        self.bus.subscribe("topn", self._on_topn)
        # operator flush surface (data-node SnapshotService analog):
        # persists memtables to parts on demand — ops tooling and tests
        # use it to bound the direct-write plane's crash-loss window
        self.bus.subscribe(
            "flush",
            lambda env: {"parts": self.measure.flush(env.get("group"))},
        )
        # per-node FODC agent surface polled by the proxy (admin/fodc.py)
        from banyandb_tpu.admin.diagnostics import DIAG_TOPIC

        self.bus.subscribe(DIAG_TOPIC, self._on_diagnostics)
        # schema anti-entropy gossip topics (cluster/schema_gossip.py)
        from banyandb_tpu.cluster import schema_gossip

        schema_gossip.register_handlers(self.bus, self.registry)

    def _on_streamagg(self, env: dict) -> dict:
        op = env.get("op", "stats")
        if op == "register":
            info = self.measure.streamagg.register(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
                max_windows=env.get("max_windows"),
                origin=env.get("origin", "manual"),
            )
            return {"registered": info, "node": self.name}
        if op == "unregister":
            removed = self.measure.streamagg.unregister(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
            )
            return {"unregistered": removed, "node": self.name}
        if op == "stats":
            return {
                "streamagg": self.measure.streamagg.stats(),
                "node": self.name,
            }
        raise ValueError(f"bad streamagg op {op!r}")

    # -- elastic-cluster control surface (docs/robustness.md) ---------------
    def _on_placement(self, env: dict) -> dict:
        """Placement-epoch surface: ``get`` reads the fence, ``set``
        adopts a cutover broadcast (ratchet-up; adopting never
        rejects — only WRITE envelopes can be stale)."""
        op = env.get("op", "get")
        if op == "set":
            e = int(env["epoch"])
            if e > self.epoch_record.epoch:
                self.epoch_record.observe(e, source="placement-set")
            return {"epoch": self.epoch_record.epoch, "node": self.name}
        if op == "get":
            return {"epoch": self.epoch_record.epoch, "node": self.name}
        raise ValueError(f"bad placement op {op!r}")

    def _on_rebalance(self, env: dict) -> dict:
        """Rebalance/repair data plane (cluster/rebalance.py mover):

        - ``flush``: drain every engine's memtables so the next
          manifest snapshot covers all acked rows as parts;
        - ``manifest``: per-shard part inventory with install-dedup
          digest keys (the sealer's part uuid when stamped, content
          sha256 otherwise — the SAME keys the sync-install dedup
          uses, so a re-ship of a listed part is always a no-op);
        - ``pull``: one CRC-able chunk of one part file (the mover
          re-ships it to the new owner through Topic.SYNC_PART)."""
        op = env.get("op")
        if op == "flush":
            return {
                "flushed": {
                    "measure": self.measure.flush(),
                    "stream": self.stream.flush(),
                    "trace": self.trace.flush(),
                }
            }
        if op == "manifest":
            parts, skipped = self._shard_manifest(int(env["shard"]))
            return {"parts": parts, "skipped": skipped}
        if op == "pull":
            return self._pull_part_chunk(env)
        if op == "pull_all":
            return self._pull_part_all(env)
        raise ValueError(f"bad rebalance op {op!r}")

    def _engine_groups(self, engine, catalog: str) -> list[str]:
        """Groups with on-disk data for one catalog: already-open TSDBs
        plus directories from a previous process life (a restarted node
        must manifest parts it has not re-opened yet)."""
        names = set(engine._tsdbs)
        cat_root = self.root / catalog
        try:
            names.update(d.name for d in cat_root.iterdir() if d.is_dir())
        except OSError:
            pass
        return sorted(names)

    def _part_digest_key(self, group: str, shard_idx: int, part) -> str:
        """Manifest identity == install-dedup identity (`_synced_part_key`
        semantics): sealer part uuid when present, else a cached content
        sha256 over the part's files."""
        sess = part.meta.get("seal_session")
        if sess:
            return f"{group}/{shard_idx}/uuid:{sess}"
        cache_key = str(part.dir)
        with self._manifest_lock:
            hit = self._manifest_digests.get(cache_key)
        if hit is None:
            files = {
                f.name: f.read_bytes()
                for f in sorted(part.dir.iterdir())
                if f.is_file()
            }
            hit = self._synced_part_digest(files)
            with self._manifest_lock:
                self._manifest_digests[cache_key] = hit
                # parts come and go with merges/retention: bound the cache
                while len(self._manifest_digests) > 4096:
                    self._manifest_digests.pop(
                        next(iter(self._manifest_digests))
                    )
        return f"{group}/{shard_idx}/{hit}"

    def _shard_manifest(self, shard_idx: int) -> "tuple[list[dict], int]":
        """-> (entries, skipped): `skipped` counts parts that vanished
        under the merge loop mid-listing — the mover treats them like
        gone pulls (another round with a fresh manifest)."""
        skipped = 0
        out: list[dict] = []
        for engine, catalog in (
            (self.measure, "measure"),
            (self.stream, "stream"),
            (self.trace, "trace"),
        ):
            for group in self._engine_groups(engine, catalog):
                try:
                    db = engine._tsdb(group)
                except Exception:  # noqa: BLE001 - foreign dir under the
                    continue  # catalog root is not a group tree
                for seg in db.segments:
                    if shard_idx >= len(seg.shards):
                        continue
                    for part in seg.shards[shard_idx].parts:
                        try:
                            files = {
                                f.name: f.stat().st_size
                                for f in sorted(part.dir.iterdir())
                                if f.is_file()
                            }
                            key = self._part_digest_key(
                                group, shard_idx, part
                            )
                        except FileNotFoundError:
                            # merged away between the parts snapshot and
                            # the stat/read: its rows live on in the
                            # merged part, visible to the NEXT manifest
                            # — skip instead of failing the whole
                            # manifest (which would read as a dead node)
                            skipped += 1
                            continue
                        out.append({
                            "key": key,
                            "catalog": catalog,
                            "group": group,
                            "segment": seg.root.name,
                            "segment_start": int(seg.start),
                            "shard": shard_idx,
                            "part": part.dir.name,
                            "files": files,
                            "min_ts": int(part.meta.get("min_ts", seg.start)),
                        })
        return out, skipped

    def _pull_part_chunk(self, env: dict) -> dict:
        import base64

        engine = {
            "stream": self.stream,
            "trace": self.trace,
        }.get(env.get("catalog", "measure"), self.measure)
        db = engine._tsdb(env["group"])
        seg = db.segment_for(int(env["segment_start"]))
        pdir = seg.shards[int(env["shard"])].root / env["part"]
        fpath = pdir / env["file"]
        # containment: the wire names a file inside THIS part dir only
        if fpath.parent != pdir or "/" in env["file"] or ".." in env["file"]:
            raise ValueError(f"bad pull file {env['file']!r}")
        offset = int(env.get("offset", 0))
        length = int(env.get("length", 1 << 20))
        try:
            with open(fpath, "rb") as fh:
                fh.seek(offset)
                blob = fh.read(length)
                eof = fh.read(1) == b""
            size = fpath.stat().st_size
        except FileNotFoundError:
            # the lifecycle merge loop rewrote this part between the
            # manifest snapshot and the pull: its rows live on in the
            # merged part, which the NEXT manifest round ships
            return {"gone": True, "data": "", "eof": True, "size": 0}
        return {
            "data": base64.b64encode(blob).decode(),
            "eof": eof,
            "size": size,
        }

    def _pull_part_all(self, env: dict) -> dict:
        """Whole-part pull in ONE reply when the part fits the bundle
        cap (per-RPC latency dominates small-part moves on slow
        loopbacks); oversize parts return truncated=True and the mover
        falls back to per-file chunk pulls."""
        import base64

        engine = {
            "stream": self.stream,
            "trace": self.trace,
        }.get(env.get("catalog", "measure"), self.measure)
        db = engine._tsdb(env["group"])
        seg = db.segment_for(int(env["segment_start"]))
        pdir = seg.shards[int(env["shard"])].root / env["part"]
        cap = int(env.get("cap_bytes", 24 << 20))
        try:
            files = sorted(f for f in pdir.iterdir() if f.is_file())
            if sum(f.stat().st_size for f in files) > cap:
                return {"truncated": True, "files": {}}
            return {
                "truncated": False,
                "files": {
                    f.name: base64.b64encode(f.read_bytes()).decode()
                    for f in files
                },
            }
        except FileNotFoundError:
            # merged away between manifest and pull (see _pull_part_chunk)
            return {"gone": True, "truncated": False, "files": {}}

    def _on_diagnostics(self, env: dict) -> dict:
        from banyandb_tpu.admin.diagnostics import DiagnosticsCollector

        return DiagnosticsCollector(self.root).collect(
            include_threads=bool(env.get("include_threads"))
        )

    # -- stream plane (stream svc_data analog) ------------------------------
    def _fence_epoch(self, env: dict, site: str) -> None:
        """Stale-epoch write fence: envelopes stamped with an older
        placement epoch than this node has witnessed are REJECTED
        (retryable kind="stale_epoch" on the wire) — a mover and a
        straggling liaison can never double-apply a write across a
        rebalance cutover.  Fresher epochs are adopted (and persisted):
        epoch knowledge gossips with ordinary traffic, so a node that
        missed the cutover broadcast still converges."""
        e = env.get("placement_epoch")
        if e is not None:
            self.epoch_record.observe(int(e), source=site)

    def _on_stream_write(self, env: dict) -> dict:
        self._fence_epoch(env, "stream-write")
        # schema piggybacked on first contact (streams live outside the
        # core registry kinds; liaison ships the spec with writes)
        if "schema" in env:
            item = env["schema"]
            try:
                self.stream.get_stream(item["group"], item["name"])
            except KeyError:
                self.stream.create_stream(serde.stream_schema_from_json(item))
        self.disk.check_write()
        import time as _time

        t0 = _time.perf_counter()
        # the write runs under the stamped tenant too: the engine's
        # cache invalidations and QoS accounting must land in the SAME
        # partition the tenant's queries read from
        with self._tenant_scope(env, env["group"]):
            n = self.stream.write(
                env["group"], env["name"],
                serde.elements_from_json(env["elements"]),
            )
        self._observe_write("stream", t0)
        return {"written": n}

    def _on_stream_query(self, env: dict) -> dict:
        import base64

        self._check_deadline(env)
        # queries fence too: a scatter routed on a superseded placement
        # map would read shards this node no longer (or not yet) owns —
        # and the fence's adopt-if-fresher half means epoch knowledge
        # gossips with READ traffic, not just writes
        self._fence_epoch(env, "stream-query")
        req = serde.query_request_from_json(env["request"])
        shard_ids = set(env["shards"]) if env.get("shards") is not None else None
        try:
            # Only the schema lookup is forgiving: this node may simply
            # never have learned the stream (schemas arrive with writes /
            # SCHEMA_SYNC) and must not fail the whole scatter.  Errors
            # from the query itself (e.g. typo'd predicate tags) propagate
            # exactly like standalone mode.
            self.stream.get_stream(req.groups[0], req.name)
        except KeyError:
            return {"data_points": []}
        tracer = self._node_tracer(req, env)
        with self._tenant_scope(env, req.groups[0] if req.groups else ""):
            res = self.stream.query(req, shard_ids=shard_ids, tracer=tracer)
        out = {
            "data_points": [
                {
                    **dp,
                    "tags": serde.tags_to_json(dp["tags"]),
                    "body": base64.b64encode(dp["body"]).decode(),
                }
                for dp in res.data_points
            ]
        }
        if tracer is not None:
            out["trace"] = tracer.finish()
        return out

    # -- trace plane (trace svc_data analog) --------------------------------
    def _on_trace_write(self, env: dict) -> dict:
        self._fence_epoch(env, "trace-write")
        if "schema" in env:
            item = env["schema"]
            try:
                self.trace.get_trace(item["group"], item["name"])
            except KeyError:
                self.trace.create_trace(serde.trace_schema_from_json(item))
        self.disk.check_write()
        import time as _time

        t0 = _time.perf_counter()
        with self._tenant_scope(env, env["group"]):
            n = self.trace.write(
                env["group"], env["name"],
                serde.spans_from_json(env["spans"]),
                ordered_tags=tuple(env.get("ordered_tags", ())),
            )
        self._observe_write("trace", t0)
        return {"written": n}

    def _on_trace_query(self, env: dict) -> dict:
        try:
            # forgiving only for the schema lookup: an ordinary not-found
            # must not turn into a shard-dependent error; real query
            # errors propagate like standalone mode
            self.trace.get_trace(env["group"], env["name"])
        except KeyError:
            return {"spans": []}
        spans = self.trace.query_by_trace_id(
            env["group"], env["name"], env["trace_id"]
        )
        return {"spans": serde.spans_to_json(spans)}

    def _on_trace_query_ordered(self, env: dict) -> dict:
        """Ordered retrieval map phase: local sidx scan, results carry
        their ordering keys for the liaison's k-way merge."""
        from banyandb_tpu.api.model import TimeRange

        self._fence_epoch(env, "trace-query-ordered")
        try:
            self.trace.get_trace(env["group"], env["name"])
        except KeyError:
            return {"results": []}
        keyed = self.trace.query_ordered(
            env["group"], env["name"], env["order_tag"],
            TimeRange(env["begin"], env["end"]),
            lo=env.get("lo"), hi=env.get("hi"),
            asc=bool(env.get("asc", False)),
            limit=int(env.get("limit", 20)),
            with_keys=True,
        )
        return {"results": [[int(k), tid] for k, tid in keyed]}

    def _on_trace_query_exec(self, env: dict) -> dict:
        """Full trace query surface map phase: the complete QueryRequest
        (criteria/projection/order-by/limit+offset) runs against owned
        shards; span rows carry their sidx keys so the liaison's partial
        merge preserves sidx order across nodes."""
        import base64

        self._check_deadline(env)
        self._fence_epoch(env, "trace-query-exec")
        req = serde.query_request_from_json(env["request"])
        shard_ids = set(env["shards"]) if env.get("shards") is not None else None
        try:
            # forgiving only for the schema lookup (see _on_stream_query)
            self.trace.get_trace(req.groups[0], req.name)
        except KeyError:
            return {"data_points": []}
        tracer = self._node_tracer(req, env)
        with self._tenant_scope(env, req.groups[0] if req.groups else ""):
            res = self.trace.query(req, shard_ids=shard_ids, tracer=tracer)
        out = {
            "data_points": [
                {
                    **dp,
                    "tags": serde.tags_to_json(dp["tags"]),
                    "span": base64.b64encode(dp["span"]).decode(),
                }
                for dp in res.data_points
            ]
        }
        if tracer is not None:
            out["trace"] = tracer.finish()
        return out

    # -- write plane --------------------------------------------------------
    @staticmethod
    def _observe_write(model: str, t0: float) -> None:
        """write_ms{model} on the node-local meter: in worker mode this
        is what gives the merged /metrics its per-worker write labels."""
        import time as _time

        from banyandb_tpu.obs.metrics import global_meter

        global_meter().observe(
            "write_ms", (_time.perf_counter() - t0) * 1000, {"model": model}
        )

    def _on_measure_write(self, env: dict) -> dict:
        import time as _time

        self._fence_epoch(env, "measure-write")
        self.disk.check_write()
        req = serde.write_request_from_json(env["request"])
        t0 = _time.perf_counter()
        with self._tenant_scope(env, req.group):
            n = self.measure.write(req)
        self._observe_write("measure", t0)
        return {"written": n}

    def _on_measure_write_columns(self, env: dict) -> dict:
        """Columnar write envelope on the data-node role: the vectorized
        ingest wire shape the standalone server already speaks, decoded
        with the shared serde codec.  The shard-owning worker processes
        (cluster/workers.py) receive their per-shard ingest slices on
        this topic."""
        import time as _time

        self._fence_epoch(env, "measure-write-cols")
        self.disk.check_write()
        t0 = _time.perf_counter()
        with self._tenant_scope(env, env.get("group", "")):
            n = self.measure.write_columns(
                **serde.write_columns_env_decode(env)
            )
        self._observe_write("measure", t0)
        return {"written": n}

    def _on_topn(self, env: dict) -> dict:
        """TopN query over this node's pre-aggregated windows
        (TopNService analog, node-local half): ranked items carry their
        entities so a scatter caller can merge — entities are
        shard-routed, so cross-node entity sets are disjoint and the
        merge is concat + re-rank."""
        from banyandb_tpu.api.model import TimeRange
        from banyandb_tpu.models import topn as topn_mod

        rules = {r.name for r in self.registry.list_topn(env["group"])}
        if env["name"] not in rules:
            raise KeyError(
                f"topn rule {env['name']} not found in group {env['group']}"
            )
        ranked = topn_mod.query_topn(
            self.measure,
            env["group"],
            env["name"],
            TimeRange(*env["time_range"]),
            n=env.get("n", 10),
            direction=env.get("direction", "desc"),
            agg=env.get("agg", "sum"),
            # JSON round-trip turns the (tag, op, value) triples into
            # lists; query_topn wants tuples
            conditions=tuple(
                (c[0], c[1], c[2]) for c in env.get("conditions", ())
            ),
        )
        return {
            "items": [
                {"entity": list(ent), "value": val} for ent, val in ranked
            ]
        }

    # -- query plane --------------------------------------------------------
    @staticmethod
    def _check_deadline(env: dict) -> None:
        """Liaison->data-node deadline propagation: the scatter envelope
        carries the query's REMAINING budget at send time; work whose
        budget is already gone is refused up front (kind="deadline" on
        the wire — the liaison degrades instead of evicting this node)
        rather than scanned into a reply nobody will read."""
        import time as _time

        d = env.get("deadline_ms")
        abs_d = env.get("deadline_unix_ms")
        expired = (d is not None and float(d) <= 0) or (
            # the absolute wall deadline catches budget spent while the
            # request sat in this node's executor queue (the relative
            # form is a send-time snapshot and cannot)
            abs_d is not None and float(abs_d) <= _time.time() * 1000.0
        )
        if expired:
            from banyandb_tpu.cluster.faults import DeadlineExceeded

            raise DeadlineExceeded(
                "query deadline exhausted before node scan"
            )

    def _node_tracer(self, req, env: "dict | None" = None):
        """Per-node tracer when the request is traced OR the scatter
        caller runs its own tracer (``want_subtree`` on the envelope —
        the liaison stamps it whenever it holds a real tracer, e.g. the
        always-on serving-surface one): this node runs its own span tree
        and ships the subtree back in the reply for the caller's
        cluster-wide merge (pkg/query/tracer propagation,
        dquery/measure.go:104 analog).  The subtree rides the BUS reply,
        never the user-facing result, so untraced responses are
        byte-identical either way."""
        if not req.trace and not (env or {}).get("want_subtree"):
            return None
        from banyandb_tpu.obs.tracer import Tracer

        return Tracer(f"data:{self.name}")

    @staticmethod
    def _tenant_scope(env: dict, group: str):
        """Bind the envelope's stamped tenant (else derive from the
        group) for the handler's work, so this node's serving-cache
        reads/writes land in the tenant's OWN partition
        (docs/robustness.md "Multi-tenant QoS")."""
        from banyandb_tpu.qos import tenancy

        return tenancy.tenant_scope(
            env.get("tenant") or tenancy.tenant_of_group(group)
        )

    def _on_measure_query_partial(self, env: dict) -> dict:
        self._check_deadline(env)
        self._fence_epoch(env, "measure-query-partial")
        req = serde.query_request_from_json(env["request"])
        shard_ids = set(env["shards"]) if env.get("shards") is not None else None
        hist_range = tuple(env["hist_range"]) if env.get("hist_range") else None
        tracer = self._node_tracer(req, env)
        with self._tenant_scope(env, req.groups[0] if req.groups else ""):
            partials = self.measure.query_partials(
                req, shard_ids=shard_ids, hist_range=hist_range,
                tracer=tracer,
            )
        out = {"partials": serde.partials_to_json(partials)}
        if tracer is not None:
            out["trace"] = tracer.finish()
        return out

    def _on_measure_query_raw(self, env: dict) -> dict:
        self._check_deadline(env)
        self._fence_epoch(env, "measure-query-raw")
        req = serde.query_request_from_json(env["request"])
        shard_ids = set(env["shards"]) if env.get("shards") is not None else None
        tracer = self._node_tracer(req, env)
        with self._tenant_scope(env, req.groups[0] if req.groups else ""):
            res = self.measure.query(req, shard_ids=shard_ids, tracer=tracer)
        out = {"data_points": res.data_points}
        if tracer is not None:
            out["trace"] = tracer.finish()
        return out

    # -- schema sync (schemaserver/gossip analog, push-based) ---------------
    def _on_schema_sync(self, env: dict) -> dict:
        from banyandb_tpu.api import schema as schema_mod

        kind = env["kind"]
        cls = schema_mod._KINDS[kind]
        obj = schema_mod._from_jsonable(cls, env["item"])
        rev = self.registry._put(kind, obj)
        return {"revision": self.registry.revision, "obj_rev": rev}

    # -- chunked part sync (sub/chunked_sync.go analog) ----------------------
    def _on_sync_part(self, env: dict) -> dict:
        import base64

        phase = env["phase"]
        session = env["session"]
        if phase == "begin":
            # the part-ship plane is fenced too: a straggling sender's
            # sealed part from before a cutover must not install on an
            # owner the new placement no longer routes reads to
            self._fence_epoch(env, "sync-part")
            # Stage OUTSIDE the shard dir: opening the shard GCs unlisted
            # entries, which would eat an in-flight session.
            dest = self.root / ".sync-staging" / session
            dest.mkdir(parents=True, exist_ok=True)
            self._sync_sessions[session] = {
                "dir": dest,
                "files": {},
                "group": env["group"],
                "segment": env["segment"],
                "shard": env["shard"],
            }
            return {"accepted": True}
        if phase == "abort":
            # sender gave up mid-session (e.g. the pulled part vanished
            # under a merge): drop the staged state
            import shutil as _shutil

            state = self._sync_sessions.pop(session, None)
            if state is not None:
                _shutil.rmtree(state["dir"], ignore_errors=True)
            return {"aborted": True}
        state = self._sync_sessions.get(session)
        if state is None:
            raise KeyError(f"unknown sync session {session}")
        if phase == "chunk":
            blob = base64.b64decode(env["data"])
            if zlib.crc32(blob) != env["crc32"]:
                raise ValueError("chunk CRC mismatch")
            buf = state["files"].setdefault(env["file"], bytearray())
            assert len(buf) == env["offset"], "out-of-order chunk"
            buf.extend(blob)
            return {"received": len(blob)}
        if phase == "files":
            # batched small-part form (the rebalance mover): every file
            # of the part in one envelope, CRC'd per file — cuts the
            # per-RPC latency tax a chunk-per-call stream pays on small
            # parts
            total = 0
            for fname, data in env["files"].items():
                blob = base64.b64decode(data)
                if zlib.crc32(blob) != env["crc32s"][fname]:
                    raise ValueError(f"file CRC mismatch for {fname}")
                state["files"][fname] = bytearray(blob)
                total += len(blob)
            return {"received": total}
        if phase == "finish":
            # materialize the part dir, then introduce it into the shard
            # (FinishSync -> introduce, §3.2 of SURVEY.md)
            import json as _json

            state = self._sync_sessions.pop(session)
            group = state["group"]
            shard_idx = int(state["shard"].split("-")[1])
            # idempotence, same contract as the streaming path: a re-ship
            # after a sender crash-before-progress-write installs nothing
            files = {f: bytes(b) for f, b in state["files"].items()}
            pmeta0 = _json.loads(files.get("metadata.json", b"{}"))
            digest = self._synced_part_key(group, shard_idx, pmeta0, files)
            with self._installed_lock:
                if digest in self._installed:
                    return {"introduced": "", "duplicate": True}
                self._installed[digest] = None
            try:
                # disk-fault boundary (cluster/faults.py): the part
                # materialization is the JSON sync plane's spool write —
                # ENOSPC here must surface as a failed FinishSync the
                # sender retries, never a half-installed part
                from banyandb_tpu.cluster import faults as _faults

                _faults.check_disk("sync-part-finish")
                for fname, buf in files.items():
                    fs.atomic_write(state["dir"] / fname, buf)
                # catalog from the part's own metadata (parts carry their
                # resource kind), mirroring the streaming install path
                pmeta = _json.loads(files.get("metadata.json", b"{}"))
                catalog = pmeta.get(
                    "catalog",
                    "stream" if "stream" in pmeta
                    else ("trace" if "trace" in pmeta else "measure"),
                )
                if catalog not in ("measure", "stream", "trace"):
                    raise ValueError(f"unsupported part catalog {catalog!r}")
                min_ts = int(env["segment_start_millis"])
                part_name, part_dir = self._introduce_part_dir(
                    state["dir"], group, shard_idx, min_ts, catalog=catalog
                )
            except BaseException:
                with self._installed_lock:
                    self._installed.pop(digest, None)
                raise
            self._post_install_aux(
                catalog, group, pmeta, min_ts, shard_idx, part_name, part_dir
            )
            self._persist_installed_digests()
            return {"introduced": part_name}
        raise ValueError(f"bad sync phase {phase}")

    def _introduce_part_dir(
        self,
        staged_dir,
        group: str,
        shard_idx: int,
        segment_start_millis: int,
        catalog: str = "measure",
    ) -> "tuple[str, Path]":
        """Move a fully-staged part dir into the owning engine's shard +
        publish + register series (shared by the JSON path and streaming
        chunked sync).  catalog routes measure vs stream parts to their
        separate TSDB trees."""
        import os

        from banyandb_tpu.storage.part import Part

        engine = {
            "stream": self.stream,
            "trace": self.trace,
        }.get(catalog, self.measure)
        db = engine._tsdb(group)
        seg = db.segment_for(segment_start_millis)
        shard = seg.shards[shard_idx]
        with shard._lock:
            shard._epoch += 1
            part_name = f"part-{shard._epoch:016x}"
            final = shard.root / part_name
            os.rename(staged_dir, final)
            part = shard._parts[part_name] = Part(final)
            shard._publish()
        self._register_synced_series(seg, part)
        return part_name, final

    def _synced_part_digest(self, files: dict) -> str:
        import hashlib

        h = hashlib.sha256()
        for fname in sorted(files):
            h.update(fname.encode())
            h.update(b"\0")
            h.update(files[fname])
            h.update(b"\0")
        return h.hexdigest()

    def _synced_part_key(
        self, group: str, shard_idx: int, pmeta: dict, files: dict
    ) -> str:
        """Idempotence key for an installed synced part.  Prefers the
        sealer's part uuid (``seal_session``, unique per wqueue seal):
        a re-shipped part after an ack-lost sender crash dedupes without
        hashing megabytes, and even if a metadata byte differs between
        deliveries.  Parts from sealers that stamp no uuid (tier
        migration meta_patch path, pre-uuid senders) fall back to the
        full content digest."""
        sess = pmeta.get("seal_session")
        if sess:
            return f"{group}/{shard_idx}/uuid:{sess}"
        return f"{group}/{shard_idx}/{self._synced_part_digest(files)}"

    def _persist_installed_digests(self) -> None:
        """Flush the installed-digest record (call with new digests already
        in self._installed; one write covers a whole sync batch)."""
        with self._installed_lock:
            # bound the sidecar; dict preserves insertion order, so this
            # evicts the oldest digests — far beyond any re-ship window
            while len(self._installed) > 8192:
                del self._installed[next(iter(self._installed))]
            # write under the lock: concurrent batch persists must not
            # land out of order and drop each other's digests from disk
            fs.atomic_write_json(
                self.root / ".sync-installed.json", list(self._installed)
            )

    def install_synced_parts(self, meta, parts) -> None:
        """Streaming ChunkedSyncService install callback
        (cluster/chunked_sync.py): write each part's files to staging,
        then introduce into the shard owning meta.shard_id.  The target
        segment comes from each part's min timestamp (the reference's
        receiver does the same: parts land in their time's segment).
        Idempotent per part content hash: re-delivery after a partial
        ship installs nothing twice."""
        import json as _json
        import uuid as _uuid

        # streaming-path epoch fence: the sender's placement epoch rides
        # a @epoch=N suffix on the metadata topic (the proto has no
        # spare field) — a straggling liaison's sealed part from before
        # a cutover must not install on an owner the new placement no
        # longer routes reads to
        from banyandb_tpu.cluster.chunked_sync import parse_epoch_topic

        _bare, epoch = parse_epoch_topic(getattr(meta, "topic", "") or "")
        if epoch is not None:
            self.epoch_record.observe(epoch, source="part-sync")
        self.disk.check_write()
        installed_any = False
        try:
            for pi, files in parts:
                installed_any |= self._install_one_synced_part(
                    meta, pi, files, _json, _uuid
                )
        finally:
            if installed_any:
                self._persist_installed_digests()

    def _install_one_synced_part(self, meta, pi, files, _json, _uuid) -> bool:
        if "metadata.json" not in files:
            raise ValueError("part missing metadata.json")
        pmeta = _json.loads(files["metadata.json"])
        group = meta.group or pmeta.get("group")
        digest = self._synced_part_key(group, int(meta.shard_id), pmeta, files)
        with self._installed_lock:
            if digest in self._installed:
                return False
            # claim in-flight under the same acquisition: a concurrent
            # re-delivery of this part must not pass the check while the
            # first install is still running
            self._installed[digest] = None
        try:
            # disk-fault boundary: staging is where a chunk-synced part
            # first touches disk; an injected ENOSPC releases the
            # digest claim below so the sender's re-ship can install
            from banyandb_tpu.cluster import faults as _faults

            _faults.check_disk("sync-install")
            staged = self.root / ".sync-staging" / _uuid.uuid4().hex
            staged.mkdir(parents=True, exist_ok=True)
            for fname, blob in files.items():
                fs.atomic_write(staged / fname, blob)
            min_ts = int(pmeta.get("min_ts", pi.min_timestamp))
            # explicit catalog from the sealer; key-sniff only for parts
            # written before the field existed
            catalog = pmeta.get(
                "catalog", "stream" if "stream" in pmeta else "measure"
            )
            if catalog not in ("measure", "stream", "trace"):
                raise ValueError(f"unsupported part catalog {catalog!r}")
            part_name, part_dir = self._introduce_part_dir(
                staged, group, int(meta.shard_id), min_ts, catalog=catalog
            )
        except BaseException:
            # failed install releases the claim so a retry can proceed
            with self._installed_lock:
                self._installed.pop(digest, None)
            raise
        self._post_install_aux(
            catalog, group, pmeta, min_ts, int(meta.shard_id), part_name, part_dir
        )
        return True

    def _post_install_aux(
        self, catalog, group, pmeta, min_ts, shard_idx, part_name, part_dir
    ) -> None:
        """Auxiliary rebuilds every installed part needs, whatever wire it
        arrived on (streaming chunked sync or the JSON SYNC_PART path):
        trace bloom+sidx, stream element-index sidecars, measure TopN
        observation."""
        import logging

        if catalog == "trace":
            try:
                self._index_trace_part(group, pmeta, min_ts, shard_idx, part_dir)
            except Exception:  # noqa: BLE001 - retrieval stays correct
                # via full scans; ordered/bloom pruning degrades
                logging.getLogger("banyandb.datanode").exception(
                    "trace index build failed for installed part %s",
                    part_dir,
                )
        elif catalog == "stream":
            # element-index/bloom sidecars for the installed part
            try:
                self.stream._build_part_index(group, part_dir, pmeta)
            except Exception:  # noqa: BLE001 - pruning is optional,
                # but silent degradation to full scans is not
                logging.getLogger("banyandb.datanode").exception(
                    "sidecar build failed for installed part %s", part_dir
                )
        else:
            self._observe_topn_part(group, pmeta, min_ts, shard_idx, part_name)
            try:
                self._observe_streamagg_part(
                    group, pmeta, shard_idx, part_dir
                )
            except Exception as exc:  # noqa: BLE001 - an install must
                # never fail over the windows; but a part whose rows
                # did NOT reach them makes every covered answer an
                # undercount, so coverage is POISONED (affected ranges
                # rescan) instead of served with a silent gap
                logging.getLogger("banyandb.datanode").exception(
                    "streamagg window update failed for installed part %s",
                    part_dir,
                )
                measure_name = pmeta.get("measure")
                if measure_name:
                    self.measure.streamagg.invalidate(
                        group, measure_name,
                        reason=f"install hook failed: {exc}",
                        # the failed part's rows may lie ABOVE the
                        # watermark: poison up to its max event ts
                        up_to=pmeta.get("max_ts"),
                    )

    def _observe_streamagg_part(
        self, group: str, pmeta: dict, shard_idx: int, part_dir
    ) -> None:
        """Feed an installed part's rows through the continuous
        streaming-aggregation windows (query/streamagg.py) — the wqueue
        drain path bypasses MeasureEngine.write, which is where direct
        writes update windows.  Install-digest idempotence upstream
        guarantees a re-shipped part reaches this hook at most once, so
        windows never double-count."""
        import numpy as np

        measure_name = pmeta.get("measure")
        if not measure_name:
            return
        needs = self.measure.streamagg.needs(group, measure_name)
        if needs is None:
            return
        if pmeta.get("rows") == 0:
            return  # wqueue row-count stamp: empty part, skip the read
        need_tags, need_fields = needs
        from banyandb_tpu.storage.part import Part

        part = Part(part_dir)
        cols = part.read(
            range(len(part.blocks)),
            tags=[t for t in need_tags if t in part.meta["tags"]],
            fields=[f for f in need_fields if f in part.meta["fields"]],
            cached=False,
        )
        n = int(cols.ts.size)
        if n == 0:
            return
        from banyandb_tpu.query.streamagg import (
            coldata_field_col,
            coldata_tag_col,
        )

        def tag_col(t: str):
            return coldata_tag_col(cols, t, n)

        def field_col(f: str):
            return coldata_field_col(cols, f, n)

        self.measure.streamagg.observe(
            group, measure_name,
            ts=cols.ts, series=cols.series, versions=cols.version,
            shards=int(shard_idx), tag_col=tag_col, field_col=field_col,
            # part identity: a registration backfill that already
            # consumed this part makes this hook a no-op for that
            # signature (the raced-install dedup contract)
            part_id=str(part_dir),
        )

    def _index_trace_part(
        self, group: str, pmeta: dict, min_ts: int, shard_idx: int, part_dir
    ) -> None:
        """Installed trace parts need the same auxiliaries local writes
        get: a trace-id bloom sidecar and sidx ordered-index entries for
        the part's tree-indexed tags (shipped in the part meta)."""
        from banyandb_tpu.index.sidx import encode_ref
        from banyandb_tpu.models.trace import write_trace_bloom
        from banyandb_tpu.storage.part import Part

        name = pmeta.get("trace")
        if not name:
            return
        t = self.registry.get_trace(group, name)
        part = Part(part_dir)
        write_trace_bloom(part, t.trace_id_tag)
        ordered = [
            rt
            for rt in pmeta.get("ordered_tags", ())
            if rt in part.meta.get("tags", ())
        ]
        if not ordered or t.trace_id_tag not in part.meta.get("tags", ()):
            return
        db = self.trace._tsdb(group)
        seg = db.segment_for(min_ts)
        cols = part.read(
            range(len(part.blocks)),
            tags=[t.trace_id_tag] + ordered,
            cached=False,
        )
        from banyandb_tpu.query.filter import decode_tag_value

        for rt in ordered:
            store = self.trace._ordered_index(group, seg, rt)
            tid_col = cols.tags[t.trace_id_tag]
            rt_col = cols.tags[rt]
            for i in range(cols.ts.size):
                raw = cols.dicts[rt][rt_col[i]]
                if not raw:
                    continue
                tid = decode_tag_value(
                    cols.dicts[t.trace_id_tag][tid_col[i]],
                    t.tag(t.trace_id_tag).type,
                )
                store.insert(
                    int.from_bytes(raw, "little", signed=True),
                    encode_ref(str(tid), int(cols.ts[i])),
                )
            store.flush()

    def _observe_topn_part(
        self, group: str, pmeta: dict, min_ts: int, shard_idx: int, part_name: str
    ) -> None:
        """Feed an installed part's rows through TopN pre-aggregation —
        the queued write path bypasses MeasureEngine.write, which is
        where per-point topn.observe normally happens.  Only runs when a
        TopN rule actually sources this measure."""
        measure_name = pmeta.get("measure")
        if not measure_name:
            return
        try:
            m = self.registry.get_measure(group, measure_name)
        except KeyError:
            return
        rules = [
            r
            for r in self.registry.list_topn(group)
            if r.source_measure == measure_name
        ]
        if not rules:
            return
        from banyandb_tpu.api.model import DataPointValue
        from banyandb_tpu.query.filter import decode_tag_value

        db = self.measure._tsdb(group)
        seg = db.segment_for(min_ts)
        part = seg.shards[shard_idx]._parts.get(part_name)
        if part is None:
            return
        need_tags = sorted(
            {t for r in rules for t in r.group_by_tag_names}
            | set(m.entity.tag_names)
        )
        need_fields = sorted({r.field_name for r in rules})
        cols = part.read(
            range(len(part.blocks)),
            tags=[t for t in need_tags if t in part.meta["tags"]],
            fields=[f for f in need_fields if f in part.meta["fields"]],
            cached=False,
        )
        for i in range(cols.ts.size):
            tags = {
                t: decode_tag_value(cols.dicts[t][cols.tags[t][i]], m.tag(t).type)
                for t in cols.tags
            }
            fields = {f: float(cols.fields[f][i]) for f in cols.fields}
            self.measure.topn.observe(
                m,
                DataPointValue(
                    ts_millis=int(cols.ts[i]),
                    tags=tags,
                    fields=fields,
                    version=int(cols.version[i]),
                ),
            )

    def _register_synced_series(self, seg, part) -> None:
        """Entity-tag series registration for a shipped part — without it,
        entity-filtered queries would prune the part's blocks away (the
        reference ships series docs alongside parts,
        banyand/measure/write_liaison.go:138 TopicMeasureSeriesSync)."""
        measure_name = part.meta.get("measure")
        if not measure_name:
            return
        try:
            m = self.registry.get_measure(
                part.meta.get("group") or self._group_of(part), measure_name
            )
        except (KeyError, RuntimeError):
            return
        entity_tags = [t for t in m.entity.tag_names if t in part.meta["tags"]]
        if len(entity_tags) != len(m.entity.tag_names):
            return
        cols = part.read(
            range(len(part.blocks)), tags=entity_tags, cached=False
        )
        import numpy as np

        series, first_idx = np.unique(cols.series, return_index=True)
        for sid, i in zip(series.tolist(), first_idx.tolist()):
            tags = {
                t: cols.dicts[t][cols.tags[t][i]] for t in entity_tags
            }
            tags["@measure"] = measure_name.encode()
            seg.series_index.insert_series(sid, tags)

    def _group_of(self, part) -> str:
        # part dirs live at <root>/measure/<group>/seg-*/shard-*/part-*
        return part.dir.parent.parent.parent.name
