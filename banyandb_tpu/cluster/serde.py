"""Wire serialization for cluster envelopes (JSON + base64 for bytes,
lists for small numpy arrays).

The partial-aggregate payload is the analog of the reference's
InternalQueryResponse with agg_return_partial
(api/proto/banyandb/measure/v1/query.proto); a binary columnar frame mode
(RawFrameSource analog) can replace this later without changing callers.
"""

from __future__ import annotations

import base64
import dataclasses
from typing import Optional

import numpy as np

from banyandb_tpu.api.model import (
    Aggregation,
    Condition,
    GroupBy,
    LogicalExpression,
    QueryRequest,
    TimeRange,
    Top,
    DataPointValue,
    WriteRequest,
)
from banyandb_tpu.query.measure_exec import Partials


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


# -- criteria ---------------------------------------------------------------


def criteria_to_json(c) -> Optional[dict]:
    if c is None:
        return None
    if isinstance(c, Condition):
        v = c.value
        if isinstance(v, bytes):
            v = {"@bytes": _b64(v)}
        elif isinstance(v, (list, tuple)):
            v = [{"@bytes": _b64(x)} if isinstance(x, bytes) else x for x in v]
        return {"kind": "cond", "name": c.name, "op": c.op, "value": v}
    if isinstance(c, LogicalExpression):
        return {
            "kind": "le",
            "op": c.op,
            "left": criteria_to_json(c.left),
            "right": criteria_to_json(c.right),
        }
    raise TypeError(type(c))


def criteria_from_json(d: Optional[dict]):
    if d is None:
        return None
    if d["kind"] == "cond":
        v = d["value"]
        if isinstance(v, dict) and "@bytes" in v:
            v = _unb64(v["@bytes"])
        elif isinstance(v, list):
            v = [
                _unb64(x["@bytes"]) if isinstance(x, dict) and "@bytes" in x else x
                for x in v
            ]
        return Condition(d["name"], d["op"], v)
    return LogicalExpression(
        d["op"], criteria_from_json(d["left"]), criteria_from_json(d["right"])
    )


# -- requests ---------------------------------------------------------------


def query_request_to_json(r: QueryRequest) -> dict:
    return {
        "groups": list(r.groups),
        "name": r.name,
        "time_range": [r.time_range.begin_millis, r.time_range.end_millis],
        "criteria": criteria_to_json(r.criteria),
        "tag_projection": list(r.tag_projection),
        "field_projection": list(r.field_projection),
        "group_by": list(r.group_by.tag_names) if r.group_by else None,
        "agg": dataclasses.asdict(r.agg) if r.agg else None,
        "top": dataclasses.asdict(r.top) if r.top else None,
        "limit": r.limit,
        "offset": r.offset,
        "order_by_ts": r.order_by_ts,
        "order_by_tag": r.order_by_tag,
        "order_by_dir": r.order_by_dir,
        "trace": r.trace,
        "stages": list(r.stages),
    }


def query_request_from_json(d: dict) -> QueryRequest:
    agg = d.get("agg")
    top = d.get("top")
    return QueryRequest(
        groups=tuple(d["groups"]),
        name=d["name"],
        time_range=TimeRange(*d["time_range"]),
        criteria=criteria_from_json(d.get("criteria")),
        tag_projection=tuple(d.get("tag_projection", ())),
        field_projection=tuple(d.get("field_projection", ())),
        group_by=GroupBy(tuple(d["group_by"])) if d.get("group_by") else None,
        agg=Aggregation(agg["function"], agg["field_name"], tuple(agg.get("quantiles", ())))
        if agg
        else None,
        top=Top(top["number"], top["field_name"], top.get("field_value_sort", "desc"))
        if top
        else None,
        limit=d.get("limit", 100),
        offset=d.get("offset", 0),
        order_by_ts=d.get("order_by_ts", ""),
        order_by_tag=d.get("order_by_tag", ""),
        order_by_dir=d.get("order_by_dir", "asc"),
        trace=d.get("trace", False),
        stages=tuple(d.get("stages", ())),
    )


def write_request_to_json(r: WriteRequest) -> dict:
    return {
        "group": r.group,
        "name": r.name,
        "points": [
            {
                "ts": p.ts_millis,
                "tags": {
                    k: {"@bytes": _b64(v)} if isinstance(v, bytes) else v
                    for k, v in p.tags.items()
                },
                "fields": dict(p.fields),
                "version": p.version,
            }
            for p in r.points
        ],
    }


def write_request_from_json(d: dict) -> WriteRequest:
    pts = []
    for p in d["points"]:
        tags = {
            k: _unb64(v["@bytes"]) if isinstance(v, dict) and "@bytes" in v else v
            for k, v in p["tags"].items()
        }
        pts.append(
            DataPointValue(p["ts"], tags, dict(p["fields"]), p.get("version", 0))
        )
    return WriteRequest(d["group"], d["name"], tuple(pts))


# -- columnar measure write envelope (Topic.MEASURE_WRITE_COLUMNS) ----------
# One codec for every consumer of the vectorized ingest wire shape: the
# standalone server, the data-node role, and the shard-owning worker
# processes (cluster/workers.py) all decode the same envelope; the
# worker pool re-encodes per-shard slices of it with the same layout.


def write_columns_env_decode(env: dict) -> dict:
    """MEASURE_WRITE_COLUMNS envelope -> write_columns kwargs.

    ts and numeric fields ride as base64-packed little-endian arrays,
    tag columns as JSON string lists or {"dict": [...], "codes": b64-i32}
    dictionary pairs (which stay dictionary-encoded end-to-end)."""
    from banyandb_tpu.models.measure import DictColumn

    ts = np.frombuffer(_unb64(env["ts"]), dtype="<i8").copy()
    versions = (
        np.frombuffer(_unb64(env["versions"]), dtype="<i8").copy()
        if env.get("versions")
        else None
    )
    tags: dict = {}
    for k, v in env.get("tags", {}).items():
        if isinstance(v, dict):
            codes = np.frombuffer(_unb64(v["codes"]), dtype="<i4")
            tags[k] = DictColumn(list(v["dict"]), codes)
        else:
            tags[k] = v
    fields = {
        k: np.frombuffer(_unb64(v), dtype="<f8").copy()
        for k, v in env.get("fields", {}).items()
    }
    return {
        "group": env["group"],
        "name": env["name"],
        "ts_millis": ts,
        "tags": tags,
        "fields": fields,
        "versions": versions,
    }


def write_columns_env_slice(cols: dict, idx: np.ndarray) -> dict:
    """Re-encode a row subset of decoded write-columns kwargs back into
    the wire envelope (the worker pool's per-shard ingest split).
    Dictionary tags keep their dict and slice only the codes."""
    from banyandb_tpu.models.measure import DictColumn

    env: dict = {
        "group": cols["group"],
        "name": cols["name"],
        "ts": _b64(
            np.ascontiguousarray(
                cols["ts_millis"][idx], dtype="<i8"
            ).tobytes()
        ),
    }
    if cols.get("versions") is not None:
        env["versions"] = _b64(
            np.ascontiguousarray(
                cols["versions"][idx], dtype="<i8"
            ).tobytes()
        )
    tags: dict = {}
    for k, v in cols.get("tags", {}).items():
        if isinstance(v, DictColumn):
            tags[k] = {
                "dict": list(v.values),
                "codes": _b64(
                    np.ascontiguousarray(
                        np.asarray(v.codes)[idx], dtype="<i4"
                    ).tobytes()
                ),
            }
        elif v is not None:
            tags[k] = [v[int(i)] for i in idx]
    if tags:
        env["tags"] = tags
    fields = {
        k: _b64(
            np.ascontiguousarray(
                np.asarray(v)[idx], dtype="<f8"
            ).tobytes()
        )
        for k, v in cols.get("fields", {}).items()
        if v is not None
    }
    if fields:
        env["fields"] = fields
    return env


# -- stream elements / trace spans (one wire format, used by the
#    standalone server AND the data-node role) ------------------------------


def elements_from_json(items: list[dict]):
    from banyandb_tpu.models.stream import ElementValue

    return [
        ElementValue(
            element_id=e["element_id"],
            ts_millis=e["ts"],
            tags=e["tags"],
            body=_unb64(e.get("body", "")),
        )
        for e in items
    ]


def spans_from_json(items: list[dict]):
    from banyandb_tpu.models.trace import SpanValue

    return [
        SpanValue(
            ts_millis=s["ts"],
            tags=s["tags"],
            span=_unb64(s.get("span", "")),
        )
        for s in items
    ]


def spans_to_json(spans: list[dict]) -> list[dict]:
    return [{**s, "span": _b64(s["span"])} for s in spans]


def tags_to_json(tags: dict) -> dict:
    """Decoded tag values -> wire form (bytes as {'@bytes': b64})."""
    return {
        k: {"@bytes": _b64(v)} if isinstance(v, bytes) else v
        for k, v in tags.items()
    }


def tags_from_json(tags: dict) -> dict:
    return {
        k: _unb64(v["@bytes"]) if isinstance(v, dict) and "@bytes" in v else v
        for k, v in tags.items()
    }


def stream_schema_from_json(item: dict):
    from banyandb_tpu.api import schema as schema_mod
    from banyandb_tpu.api.schema import Stream

    return Stream(
        group=item["group"],
        name=item["name"],
        tags=tuple(
            schema_mod.TagSpec(t["name"], schema_mod.TagType(t["type"]))
            for t in item["tags"]
        ),
        entity=tuple(item["entity"]),
    )


def trace_schema_from_json(item: dict):
    from banyandb_tpu.api import schema as schema_mod
    from banyandb_tpu.api.schema import Trace

    return Trace(
        group=item["group"],
        name=item["name"],
        tags=tuple(
            schema_mod.TagSpec(t["name"], schema_mod.TagType(t["type"]))
            for t in item["tags"]
        ),
        trace_id_tag=item["trace_id_tag"],
    )


# -- partial aggregates -----------------------------------------------------


def partials_to_json(p: Partials) -> dict:
    """Binary columnar partials frame (VERDICT r1 missing #10; the
    reference ships raw columnar frames in InternalQueryResponse
    .raw_frame_body — pkg/query/vectorized/measure/adapter.go:43).

    All numeric columns pack into ONE little-endian f64 buffer in a
    fixed layout (count, sums[f...], mins[f...], maxs[f...], hist) and
    group tuples pack into one length-prefixed string blob — the JSON
    envelope carries two base64 strings + tiny metadata instead of
    K*(3F+1) JSON floats, so envelope encode/parse is O(1) JSON tokens
    in the group count.

    Rolling upgrades: receivers accept v1 AND v2, but senders emit v2 by
    default — upgrade liaisons (the receivers of partials) before data
    nodes, or set BYDB_PARTIALS_FRAME_V1=1 on not-yet-upgraded-peer
    senders to emit the legacy shape during the transition.
    """
    from banyandb_tpu.utils import encoding as enc
    from banyandb_tpu.utils.envflag import env_flag

    if env_flag("BYDB_PARTIALS_FRAME_V1"):
        return _partials_to_json_v1(p)

    fields = sorted(p.sums.keys())
    arrays = [np.asarray(p.count, dtype="<f8")]
    arrays += [np.asarray(p.sums[f], dtype="<f8") for f in fields]
    arrays += [np.asarray(p.mins[f], dtype="<f8") for f in fields]
    arrays += [np.asarray(p.maxs[f], dtype="<f8") for f in fields]
    if p.hist is not None:
        arrays.append(np.ascontiguousarray(p.hist, dtype="<f8").ravel())
    frame = b"".join(a.tobytes() for a in arrays)
    flat_groups = [v for g in p.groups for v in g]
    out = {
        "v": 2,
        "group_tags": list(p.group_tags),
        "k": len(p.groups),
        "fields": fields,
        "groups": _b64(enc.encode_strings(flat_groups)),
        "frame": _b64(frame),
        "has_hist": p.hist is not None,
        "hist_buckets": int(p.hist.shape[1]) if p.hist is not None else 0,
        "hist_lo": p.hist_lo,
        "hist_span": p.hist_span,
        "field_stats": {f: list(v) for f, v in p.field_stats.items()},
    }
    if p.rep_key is not None:
        # [K,2] (ts,row) scan-order keys + representative tag values
        # (optional section; pre-rep peers ignore it and lose only
        # ordering/rep)
        out["rep_key"] = _b64(
            np.ascontiguousarray(p.rep_key, dtype="<i8").tobytes()
        )
        out["rep_desc"] = bool(p.rep_desc)
        if p.rep_vals is not None:
            out["rep_vals"] = {
                t: _b64(enc.encode_strings([v or b"" for v in vals]))
                for t, vals in p.rep_vals.items()
            }
    return out


def partials_from_json(d: dict) -> Partials:
    if d.get("v") != 2:  # legacy per-value JSON shape (round-1 peers)
        return _partials_from_json_v1(d)
    from banyandb_tpu.utils import encoding as enc

    fields = list(d["fields"])
    k = int(d["k"])
    ntags = len(d["group_tags"])
    flat = enc.decode_strings(_unb64(d["groups"]))
    groups = [tuple(flat[i * ntags : (i + 1) * ntags]) for i in range(k)]
    buf = np.frombuffer(_unb64(d["frame"]), dtype="<f8")
    nf = len(fields)
    off = 0

    def take(n):
        nonlocal off
        if off + n > buf.size:
            raise ValueError(
                f"partials frame truncated: need {off + n} f64s, have {buf.size}"
            )
        out = buf[off : off + n].copy()
        off += n
        return out

    count = take(k)
    sums = {f: take(k) for f in fields}
    mins = {f: take(k) for f in fields}
    maxs = {f: take(k) for f in fields}
    hist = None
    if d.get("has_hist"):
        b = int(d["hist_buckets"])
        hist = take(k * b).reshape(k, b)
    if off != buf.size:  # wire-data validation must survive python -O
        raise ValueError(
            f"partials frame length mismatch: expected {off} f64s "
            f"(k={k}, fields={nf}), got {buf.size}"
        )
    rep_key = None
    rep_vals = None
    if d.get("rep_key") is not None:
        rep_key = (
            np.frombuffer(_unb64(d["rep_key"]), dtype="<i8")
            .reshape(-1, 2)
            .copy()
        )
        if d.get("rep_vals"):
            rep_vals = {
                t: enc.decode_strings(_unb64(b))
                for t, b in d["rep_vals"].items()
            }
    return Partials(
        group_tags=tuple(d["group_tags"]),
        groups=groups,
        count=count,
        sums=sums,
        mins=mins,
        maxs=maxs,
        hist=hist,
        hist_lo=d["hist_lo"],
        hist_span=d["hist_span"],
        field_stats={f: tuple(v) for f, v in d.get("field_stats", {}).items()},
        rep_key=rep_key,
        rep_desc=bool(d.get("rep_desc")),
        rep_vals=rep_vals,
    )


def _partials_to_json_v1(p: Partials) -> dict:
    """Legacy (round-1) envelope for mixed-version transitions."""
    return {
        "group_tags": list(p.group_tags),
        "groups": [[_b64(v) for v in g] for g in p.groups],
        "count": p.count.tolist(),
        "sums": {f: a.tolist() for f, a in p.sums.items()},
        "mins": {f: a.tolist() for f, a in p.mins.items()},
        "maxs": {f: a.tolist() for f, a in p.maxs.items()},
        "hist": _b64(p.hist.astype(np.float64).tobytes()) if p.hist is not None else None,
        "hist_shape": list(p.hist.shape) if p.hist is not None else None,
        "hist_lo": p.hist_lo,
        "hist_span": p.hist_span,
        "field_stats": {f: list(v) for f, v in p.field_stats.items()},
    }


def _partials_from_json_v1(d: dict) -> Partials:
    hist = None
    if d.get("hist") is not None:
        hist = np.frombuffer(_unb64(d["hist"]), dtype=np.float64).reshape(
            d["hist_shape"]
        ).copy()
    return Partials(
        group_tags=tuple(d["group_tags"]),
        groups=[tuple(_unb64(v) for v in g) for g in d["groups"]],
        count=np.asarray(d["count"], dtype=np.float64),
        sums={f: np.asarray(a) for f, a in d["sums"].items()},
        mins={f: np.asarray(a) for f, a in d["mins"].items()},
        maxs={f: np.asarray(a) for f, a in d["maxs"].items()},
        hist=hist,
        hist_lo=d["hist_lo"],
        hist_span=d["hist_span"],
        field_stats={f: tuple(v) for f, v in d.get("field_stats", {}).items()},
    )
