"""Property-backed schema registry + event-driven watch plane.

The reference's metadata registry IS its Property engine: schema docs are
stored as properties by a schema server
(banyand/metadata/schema/schemaserver/service.go,
banyand/metadata/schema/property/client.go) and every node keeps an
event-driven schema cache fed by a WatchSchemas stream with retry
(pkg/schema/cache.go:275, api/proto/banyandb/schema/v1/internal.proto:79).

This module is the TPU-repo equivalent:

- PropertySchemaStore: dogfoods PropertyEngine as the registry's durable
  store.  Every registry create/update/delete lands as a property doc in
  the internal "_schema" group; on restart the registry replays from the
  property store.  One storage system, as upstream.
- WatchHub: in-process fan-out of schema events to any number of
  subscribed streams (schemaserver/watcher.go analog: bounded queues,
  slow watchers drop events and must re-sync).
- SchemaWatchClient: node-side cache feed.  Connects to the liaison's
  SchemaUpdateService.WatchSchemas bidi stream, replays the full schema
  set (REPLAY_DONE marker), then applies live events to the local
  registry; reconnects with backoff on any error — a node that missed a
  push converges via watch, not only via gossip.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time

from banyandb_tpu.api import schema as schema_mod
from banyandb_tpu.models.property import Property

log = logging.getLogger("banyandb.schemaplane")

SCHEMA_GROUP = "_schema"

# wire enum values (schema/v1/internal.proto SchemaEventType)
EVENT_INSERT = 1
EVENT_UPDATE = 2
EVENT_DELETE = 3
EVENT_REPLAY_DONE = 4

_QUEUE_SIZE = 512


class WatchHub:
    """Bounded fan-out of schema events (watcher.go:Broadcast analog:
    a full subscriber queue drops the event — the stream layer then owes
    the subscriber a re-sync, which SchemaWatchClient does by
    reconnecting)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[int, queue.Queue] = {}
        self._dead: set[int] = set()
        self._next = 0

    def subscribe(self) -> tuple[int, queue.Queue]:
        with self._lock:
            self._next += 1
            q: queue.Queue = queue.Queue(maxsize=_QUEUE_SIZE)
            self._subs[self._next] = q
            return self._next, q

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)
            self._dead.discard(sid)

    def is_dead(self, sid: int) -> bool:
        with self._lock:
            return sid in self._dead

    def broadcast(self, event: dict) -> None:
        with self._lock:
            subs = [
                (sid, q) for sid, q in self._subs.items()
                if sid not in self._dead
            ]
        for sid, q in subs:
            try:
                q.put_nowait(event)
            except queue.Full:
                # a lossy stream must DIE so the client re-syncs via
                # reconnect replay — silently dropping one event would
                # leave the node's cache stale forever
                with self._lock:
                    self._dead.add(sid)
                log.warning(
                    "schema watcher %d queue full; terminating its stream",
                    sid,
                )


class PropertySchemaStore:
    """Registry persistence through the Property engine.

    Wiring order matters: construct with a registry whose file
    persistence is off (root=None) — the property store is then the one
    durable home of schema docs.  A registry with its own root still
    works (both stores stay consistent), which eases migration.
    """

    def __init__(self, registry, property_engine):
        self.registry = registry
        self.prop = property_engine
        self.hub = WatchHub()
        self._replaying = False
        self._ensure_group()
        self._replay_into_registry()
        registry.watch(self._on_put)
        registry.watch_deletes(self._on_delete)

    # -- bootstrap ---------------------------------------------------------
    def _ensure_group(self) -> None:
        try:
            self.registry.get_group(SCHEMA_GROUP)
        except KeyError:
            self._replaying = True  # group creation precedes watcher wiring,
            try:  # but stay safe if called twice
                self.registry.create_group(
                    schema_mod.Group(
                        SCHEMA_GROUP,
                        schema_mod.Catalog.PROPERTY,
                        schema_mod.ResourceOpts(shard_num=1),
                    )
                )
            finally:
                self._replaying = False

    def _replay_into_registry(self) -> None:
        """Load every persisted schema doc back into the registry (restart
        path: the property shards reload from disk lazily)."""
        self._replaying = True
        try:
            for kind, cls in schema_mod._KINDS.items():
                for doc in self.prop.query(SCHEMA_GROUP, kind, limit=100000):
                    payload = json.loads(doc.tags["payload"])
                    obj = schema_mod._from_jsonable(cls, payload)
                    key = self.registry._key(obj)
                    if self.registry._store[kind].get(key) != obj:
                        self.registry._put(kind, obj)
        finally:
            self._replaying = False

    # -- registry hooks ----------------------------------------------------
    def _on_put(self, kind: str, obj, revision: int) -> None:
        if self._replaying:
            return
        key = self.registry._key(obj)
        payload = json.dumps(schema_mod._to_jsonable(obj), sort_keys=True)
        self.prop.apply(
            Property(
                group=SCHEMA_GROUP,
                name=kind,
                id=key,
                tags={"payload": payload},
            ),
            strategy="replace",
        )
        self.prop.persist_group(SCHEMA_GROUP)
        self.hub.broadcast(
            {
                "type": EVENT_UPDATE,
                "kind": kind,
                "key": key,
                "payload": payload,
                "revision": revision,
            }
        )

    def _on_delete(self, kind: str, key: str, revision: int) -> None:
        if self._replaying:
            return
        self.prop.delete(SCHEMA_GROUP, kind, key)
        self.prop.persist_group(SCHEMA_GROUP)
        self.hub.broadcast(
            {
                "type": EVENT_DELETE,
                "kind": kind,
                "key": key,
                "payload": "",
                "revision": revision,
            }
        )

    # -- snapshot for stream replay ---------------------------------------
    def replay_events(self) -> list[dict]:
        """Current schema set as INSERT events + REPLAY_DONE marker."""
        out = []
        digests = self.registry.digests()
        for kind in schema_mod._KINDS:
            for key in digests.get(kind, {}):
                payload = self.registry.export_object(kind, key)
                if payload is None:
                    continue
                out.append(
                    {
                        "type": EVENT_INSERT,
                        "kind": kind,
                        "key": key,
                        "payload": json.dumps(payload, sort_keys=True),
                        "revision": self.registry.revision,
                    }
                )
        out.append({"type": EVENT_REPLAY_DONE})
        return out


def apply_event(registry, ev: dict) -> None:
    """Apply one watch event to a local registry (cache.go handler)."""
    kind = ev.get("kind", "")
    cls = schema_mod._KINDS.get(kind)
    if cls is None:
        return
    if ev["type"] in (EVENT_INSERT, EVENT_UPDATE):
        obj = schema_mod._from_jsonable(cls, json.loads(ev["payload"]))
        key = registry._key(obj)
        if registry._store[kind].get(key) != obj:
            registry._put(kind, obj)
    elif ev["type"] == EVENT_DELETE:
        try:
            registry._delete(kind, ev["key"])
        except KeyError:
            pass


class LiaisonBarrier:
    """Cluster SchemaBarrierService backend: verifies every alive data
    node serves each key at the liaison registry's CURRENT content hash
    (barrier.proto semantics over the bus SCHEMA_GET topic — content is
    the truth, never node-local counters; liaison.schema_barrier uses
    the same rule for push acks)."""

    def __init__(self, liaison):
        self.liaison = liaison

    @property
    def _registry(self):
        return self.liaison.registry

    def _nodes(self):
        return [
            n for n in self.liaison.selector.nodes
            if n.name in self.liaison.alive
        ]

    def _poll(self, timeout_s: float, check):
        deadline = time.monotonic() + timeout_s
        while True:
            laggards = check()
            if not laggards or time.monotonic() >= deadline:
                return (not laggards), laggards
            time.sleep(0.05)

    def await_revision(self, min_revision: int, timeout_s: float):
        """Liaison registry is the source of truth for the revision
        counter; data nodes must then match its content for every key."""
        if self._registry.revision < min_revision:
            return False, [
                {
                    "node": "liaison",
                    "current_mod_revision": self._registry.revision,
                }
            ]
        digests = self._registry.digests()
        keys, revs = [], []
        from banyandb_tpu.api.grpc_server import _BARRIER_KINDS

        inv = {v: k for k, v in _BARRIER_KINDS.items()}
        for kind, objs in digests.items():
            for key in objs:
                group, _, name = key.rpartition("/")
                keys.append((inv.get(kind, kind), group, name))
                revs.append(0)
        return self.await_applied(keys, revs, timeout_s)

    def await_applied(self, keys, min_revisions, timeout_s: float):
        from banyandb_tpu.api.grpc_server import _BARRIER_KINDS
        from banyandb_tpu.cluster.bus import Topic
        from banyandb_tpu.cluster.rpc import TransportError

        addr_of = {n.name: n.addr for n in self.liaison.selector.nodes}

        def check():
            want = []
            for kind, group, name in keys:
                rkind = _BARRIER_KINDS.get(kind)
                if rkind is None:
                    raise ValueError(f"unknown schema kind {kind!r}")
                key = name if rkind == "group" else f"{group}/{name}"
                local = self._registry.stored_object_hash(rkind, key)
                want.append((kind, group, name, rkind, key, local["hash"]))
            laggards = []
            missing_local = [
                (k, g, n) for k, g, n, _rk, _key, h in want if h is None
            ]
            if missing_local:
                laggards.append(
                    {
                        "node": "liaison",
                        "current_mod_revision": self._registry.revision,
                        "missing_keys": missing_local,
                    }
                )
            for node in self._nodes():
                missing = []
                for kind, group, name, rkind, key, h in want:
                    if h is None:
                        continue
                    try:
                        r = self.liaison.transport.call(
                            addr_of[node.name],
                            Topic.SCHEMA_GET.value,
                            {"kind": rkind, "key": key},
                            timeout=5,
                        )
                    except TransportError:
                        missing.append((kind, group, name))
                        continue
                    if r.get("hash") != h:
                        missing.append((kind, group, name))
                if missing:
                    laggards.append(
                        {
                            "node": node.name,
                            "current_mod_revision": 0,
                            "missing_keys": missing,
                        }
                    )
            return laggards

        return self._poll(timeout_s, check)

    def await_deleted(self, keys, timeout_s: float):
        from banyandb_tpu.api.grpc_server import _BARRIER_KINDS
        from banyandb_tpu.cluster.bus import Topic
        from banyandb_tpu.cluster.rpc import TransportError

        addr_of = {n.name: n.addr for n in self.liaison.selector.nodes}

        def check():
            laggards = []
            for node in [{"name": "liaison", "addr": None}] + [
                {"name": n.name, "addr": addr_of[n.name]}
                for n in self._nodes()
            ]:
                present = []
                for kind, group, name in keys:
                    rkind = _BARRIER_KINDS.get(kind)
                    if rkind is None:
                        raise ValueError(f"unknown schema kind {kind!r}")
                    key = name if rkind == "group" else f"{group}/{name}"
                    if node["addr"] is None:
                        h = self._registry.stored_object_hash(rkind, key)["hash"]
                    else:
                        try:
                            h = self.liaison.transport.call(
                                node["addr"],
                                Topic.SCHEMA_GET.value,
                                {"kind": rkind, "key": key},
                                timeout=5,
                            ).get("hash")
                        except TransportError:
                            h = "unreachable"
                    if h is not None:
                        present.append((kind, group, name))
                if present:
                    laggards.append(
                        {
                            "node": node["name"],
                            "current_mod_revision": 0,
                            "still_present_keys": present,
                        }
                    )
            return laggards

        return self._poll(timeout_s, check)


class SchemaWatchClient:
    """Event-driven per-node schema cache (pkg/schema/cache.go:275
    analog): WatchSchemas stream -> local registry, with reconnect +
    exponential backoff.  The full replay on every (re)connect is the
    retry story: any missed event is healed by the next replay."""

    def __init__(self, registry, addr: str, channel_factory=None):
        self.registry = registry
        self.addr = addr
        self._channel_factory = channel_factory
        self._stop = threading.Event()
        self.synced = threading.Event()  # set after first REPLAY_DONE
        self._thread: threading.Thread | None = None
        self._call = None  # live gRPC call, cancelled on stop()
        self.reconnects = 0

    def _channel(self):
        if self._channel_factory is not None:
            return self._channel_factory(self.addr)
        import grpc

        return grpc.insecure_channel(self.addr)

    def start(self) -> "SchemaWatchClient":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        call = self._call
        if call is not None:
            try:
                call.cancel()  # unblocks the response iterator immediately
            except Exception:  # noqa: BLE001
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def wait_synced(self, timeout: float = 10.0) -> bool:
        return self.synced.wait(timeout)

    def _run(self) -> None:
        from banyandb_tpu.api import pb

        ipb = pb.schema_internal_pb2
        backoff = 0.2
        while not self._stop.is_set():
            chan = None
            # per-call termination: the request generator must die with
            # ITS call, not with the client — otherwise every reconnect
            # attempt leaks one blocked request-consumer thread for the
            # client's whole lifetime
            call_done = threading.Event()
            try:
                chan = self._channel()
                stub = chan.stream_stream(
                    "/banyandb.schema.v1.SchemaUpdateService/WatchSchemas",
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=ipb.WatchSchemasResponse.FromString,
                )

                def reqs(done=call_done):
                    yield ipb.WatchSchemasRequest()
                    # keep the stream open until this call (or the client)
                    # is done
                    while not done.is_set() and not self._stop.is_set():
                        time.sleep(0.1)

                self._call = stub(reqs())
                for resp in self._call:
                    if self._stop.is_set():
                        break
                    if resp.event_type == EVENT_REPLAY_DONE:
                        self.synced.set()
                        backoff = 0.2  # healthy stream resets the backoff
                        continue
                    ev = {
                        "type": resp.event_type,
                        "kind": resp.property.metadata.name,
                        "key": resp.property.id,
                        "payload": "",
                    }
                    for tag in resp.property.tags:
                        if tag.key == "payload":
                            ev["payload"] = tag.value.str.value
                    apply_event(self.registry, ev)
            except Exception as e:  # noqa: BLE001 - reconnect loop
                if not self._stop.is_set():
                    log.debug("schema watch stream error (%s); retrying", e)
            finally:
                call_done.set()
                call = self._call
                self._call = None
                if call is not None:
                    try:
                        call.cancel()
                    except Exception:  # noqa: BLE001
                        pass
                if chan is not None:
                    try:
                        chan.close()
                    except Exception:  # noqa: BLE001
                        pass
            if self._stop.is_set():
                return
            self.reconnects += 1
            self._stop.wait(backoff)
            backoff = min(backoff * 2, 8.0)
