"""Hinted handoff: per-down-node write spools with replay on recovery.

Analog of banyand/trace/handoff_controller.go:42,82 + handoff_storage.go,
generalized to any write envelope: when a replica is unreachable, its
envelopes spool to disk (JSON lines, size-capped, oldest-dropped); when
the node comes back (probe), the spool replays in order.
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path
from typing import Callable

from banyandb_tpu.cluster import faults

log = logging.getLogger("banyandb.handoff")


class HandoffController:
    def __init__(
        self,
        root: str | Path,
        *,
        max_bytes_per_node: int = 256 << 20,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_bytes = max_bytes_per_node
        self._lock = threading.Lock()

    def _spool_path(self, node: str) -> Path:
        return self.root / f"{node}.spool"

    def spool(self, node: str, topic: str, envelope: dict) -> None:
        """Append one missed delivery for `node` (size-capped)."""
        line = json.dumps({"topic": topic, "envelope": envelope}) + "\n"
        # disk-fault boundary (cluster/faults.py): ENOSPC raises before
        # the append; a "short" decision tears the write mid-line — the
        # corrupt trailing record is skipped at replay, never a crash
        torn = faults.check_disk("handoff-spool")
        with self._lock:
            path = self._spool_path(node)
            self._repair_torn_tail(path)
            size = path.stat().st_size if path.exists() else 0
            if size + len(line) > self.max_bytes:
                # cap by dropping the oldest half (the reference drops
                # oldest entries when its spool cap is hit)
                lines = path.read_text().splitlines(keepends=True)
                keep = lines[len(lines) // 2 :]
                path.write_text("".join(keep))
            with open(path, "a") as f:
                if torn:
                    f.write(line[: max(len(line) // 2, 1)])
                    raise OSError("injected short write at handoff spool")
                f.write(line)

    @staticmethod
    def _repair_torn_tail(path: Path) -> None:
        """Terminate a torn final record (crash/short write mid-line) so
        the next append starts a FRESH line — otherwise one torn byte
        would merge with the next record and corrupt it too.  The torn
        record itself is dropped at replay (it was never acked)."""
        try:
            if not path.exists() or path.stat().st_size == 0:
                return
            with open(path, "rb") as f:
                f.seek(-1, 2)
                torn_tail = f.read(1) != b"\n"
            if torn_tail:
                with open(path, "ab") as f:
                    f.write(b"\n")
        except OSError:
            pass

    def pending(self, node: str) -> int:
        path = self._spool_path(node)
        if not path.exists():
            return 0
        with open(path) as f:
            return sum(1 for _ in f)

    def replay(self, node: str, deliver: Callable[[str, dict], None]) -> int:
        """Drain the spool through `deliver(topic, envelope)`.

        Entries that fail again stay spooled (delivery stops at the first
        failure to preserve order). Returns replayed count.
        """
        with self._lock:
            path = self._spool_path(node)
            if not path.exists():
                return 0
            lines = path.read_text().splitlines()
        done = 0
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                # a torn append (crash/short write mid-line) leaves one
                # corrupt record; it was never acked as spooled, so it
                # drops instead of wedging every replay after it
                log.warning("handoff spool for %s: dropping corrupt line", node)
                done += 1
                continue
            try:
                deliver(rec["topic"], rec["envelope"])
            except Exception:
                break
            done += 1
        from collections import Counter

        with self._lock:
            # the spool may have grown (or been cap-trimmed) while
            # deliveries ran outside the lock: rewrite from the CURRENT
            # file, removing one occurrence per delivered entry, so a
            # concurrently spooled copy is never silently dropped
            current = (
                path.read_text().splitlines() if path.exists() else []
            )
            consumed = Counter(lines[:done])
            rest = []
            for ln in current:
                if consumed.get(ln, 0):
                    consumed[ln] -= 1
                    continue
                rest.append(ln)
            if rest:
                # same disk-fault boundary as spool(): an ENOSPC on the
                # rewrite raises with the spool file intact — delivered
                # entries replay again, and every handler on this plane
                # is an idempotent repair, so over-delivery is safe
                faults.check_disk("handoff-spool")
                self._spool_path(node).write_text("\n".join(rest) + "\n")
            else:
                self._spool_path(node).unlink(missing_ok=True)
        return done
