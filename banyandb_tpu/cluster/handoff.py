"""Hinted handoff: per-down-node write spools with replay on recovery.

Analog of banyand/trace/handoff_controller.go:42,82 + handoff_storage.go,
generalized to any write envelope: when a replica is unreachable, its
envelopes spool to disk (JSON lines, size-capped, oldest-dropped); when
the node comes back (probe), the spool replays in order.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Callable


class HandoffController:
    def __init__(
        self,
        root: str | Path,
        *,
        max_bytes_per_node: int = 256 << 20,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_bytes = max_bytes_per_node
        self._lock = threading.Lock()

    def _spool_path(self, node: str) -> Path:
        return self.root / f"{node}.spool"

    def spool(self, node: str, topic: str, envelope: dict) -> None:
        """Append one missed delivery for `node` (size-capped)."""
        line = json.dumps({"topic": topic, "envelope": envelope}) + "\n"
        with self._lock:
            path = self._spool_path(node)
            size = path.stat().st_size if path.exists() else 0
            if size + len(line) > self.max_bytes:
                # cap by dropping the oldest half (the reference drops
                # oldest entries when its spool cap is hit)
                lines = path.read_text().splitlines(keepends=True)
                keep = lines[len(lines) // 2 :]
                path.write_text("".join(keep))
            with open(path, "a") as f:
                f.write(line)

    def pending(self, node: str) -> int:
        path = self._spool_path(node)
        if not path.exists():
            return 0
        with open(path) as f:
            return sum(1 for _ in f)

    def replay(self, node: str, deliver: Callable[[str, dict], None]) -> int:
        """Drain the spool through `deliver(topic, envelope)`.

        Entries that fail again stay spooled (delivery stops at the first
        failure to preserve order). Returns replayed count.
        """
        with self._lock:
            path = self._spool_path(node)
            if not path.exists():
                return 0
            lines = path.read_text().splitlines()
        done = 0
        for line in lines:
            rec = json.loads(line)
            try:
                deliver(rec["topic"], rec["envelope"])
            except Exception:
                break
            done += 1
        with self._lock:
            rest = lines[done:]
            if rest:
                self._spool_path(node).write_text("\n".join(rest) + "\n")
            else:
                self._spool_path(node).unlink(missing_ok=True)
        return done
