"""bydbctl-analog CLI (bydbctl/internal/cmd surface, argparse flavor).

    python -m banyandb_tpu.cli --addr 127.0.0.1:17912 health
    ... group create sw --catalog measure --shards 2
    ... measure create sw cpm --tags svc:string --fields v:float --entity svc
    ... write sw cpm --point '{"ts": 1700000000000, "tags": {"svc": "a"}, "fields": {"v": 1}}'
    ... query "SELECT sum(v) FROM MEASURE cpm IN sw GROUP BY svc"
    ... snapshot
"""

from __future__ import annotations

import argparse
import json
import sys

from banyandb_tpu.cluster.rpc import GrpcTransport
from banyandb_tpu.cluster.bus import Topic
from banyandb_tpu.server import (
    TOPIC_METRICS,
    TOPIC_QL,
    TOPIC_REGISTRY,
    TOPIC_SLOWLOG,
    TOPIC_SNAPSHOT,
)


def _call(args, topic: str, envelope: dict) -> dict:
    t = GrpcTransport()
    try:
        return t.call(args.addr, topic, envelope, timeout=args.timeout)
    finally:
        t.close()


def _parse_specs(spec: str) -> list[dict]:
    out = []
    for item in spec.split(","):
        name, _, typ = item.partition(":")
        out.append({"name": name, "type": typ or "string"})
    return out


def render_explain(reply: dict) -> str:
    """Deterministic text rendering of one traced query reply: the
    logical plan tree, the serve path, and the adaptive planner's
    decision with estimated vs actual rows (query/planner).  No
    durations — the output is pinned by goldens
    (tests/test_planner.py)."""
    from banyandb_tpu.obs.tracer import find_span

    trace = (reply.get("result") or {}).get("trace") or {}
    tree = trace.get("span_tree") or {}
    served = reply.get("served", "scan")
    lines = ["plan:"]
    plan_text = trace.get("plan") or "(no plan text)"
    lines.extend("  " + ln for ln in plan_text.splitlines())
    pspan = find_span(tree, "planner")
    ptags = (pspan or {}).get("tags") or {}
    rspan = find_span(tree, "reduce")
    rtags = (rspan or {}).get("tags") or {}
    # executed path: the reduce span's ground truth when a scan ran,
    # else the serve class (materialized fold / cache replay)
    path = rtags.get("path") if served == "scan" else served
    lines.append(f"path: {path or served} (served: {served})")
    if pspan is not None:
        est = ptags.get("est_rows", "-")
        actual = ptags.get("actual_rows", "-")
        lines.append("planner:")
        lines.append(f"  estimated rows: {est}  actual rows: {actual}")
        lines.append(
            f"  estimated groups: {ptags.get('est_groups', '-')}"
            f"  group method: {ptags.get('group_method', 'auto')}"
        )
        lines.append(
            f"  selectivity: {ptags.get('selectivity', '-')}"
            f"  zone pre-pass: "
            f"{'on' if ptags.get('zone_prepass') else 'off'}"
            f"  parts: {ptags.get('parts', '-')}"
        )
    else:
        lines.append(
            "planner: (no scan planned — materialized fold, cache "
            "replay, raw rows, or BYDB_PLANNER=0)"
        )
    sspan = find_span(tree, "streamagg")
    if sspan is not None and (sspan.get("tags") or {}).get("signature"):
        st = sspan["tags"]
        lines.append("materialized:")
        lines.append(f"  signature: {st.get('signature')}")
        lines.append(
            f"  coverage: {st.get('coverage')}"
            f"  windows: {st.get('windows', '-')}"
        )
    return "\n".join(lines)


def trace_search_ql(
    group: str,
    name: str,
    *,
    tags: str = "*",
    where=(),
    order_by: str = "",
    desc: bool = False,
    limit: int = 20,
    offset: int = 0,
    from_ms=None,
    to_ms=None,
) -> str:
    """Compose one BydbQL trace query from CLI/gateway search fields —
    shared by `cli.py trace search` and `GET /api/v1/trace/search` so
    the two front doors cannot drift.  [from_ms, to_ms) is half-open,
    matching the engine's TimeRange."""
    parts = [f"SELECT {tags} FROM TRACE {name} IN {group}"]
    if from_ms is not None:
        parts.append(f"TIME >= {int(from_ms)}")
        if to_ms is not None:
            parts.append(f"AND TIME < {int(to_ms)}")
    elif to_ms is not None:
        parts.append(f"TIME < {int(to_ms)}")
    conds = [w for w in where if w and w.strip()]
    if conds:
        parts.append("WHERE " + " AND ".join(conds))
    if order_by:
        parts.append(f"ORDER BY {order_by} {'DESC' if desc else 'ASC'}")
    parts.append(f"LIMIT {int(limit)}")
    if offset:
        parts.append(f"OFFSET {int(offset)}")
    return " ".join(parts)


# the pre-canned slowlog --from-db query: slowest self-traced queries
# first (duration_us is the sidx ordering key — docs/observability.md
# "Self-trace")
SELF_QUERY_QL = (
    "SELECT * FROM TRACE self_query IN _monitoring "
    "ORDER BY duration_us DESC LIMIT {limit}"
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("bydbctl (banyandb-tpu)")
    ap.add_argument("--addr", default="127.0.0.1:17912")
    # first query against a cold server may include a TPU kernel compile
    ap.add_argument("--timeout", type=float, default=180.0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("health")
    sub.add_parser("snapshot")

    g = sub.add_parser("group")
    g.add_argument("action", choices=["create", "list"])
    g.add_argument("name", nargs="?")
    g.add_argument("--catalog", default="measure")
    g.add_argument("--shards", type=int, default=1)
    g.add_argument("--replicas", type=int, default=0)

    m = sub.add_parser("measure")
    m.add_argument("action", choices=["create", "list"])
    m.add_argument("group")
    m.add_argument("name", nargs="?")
    m.add_argument("--tags", default="")
    m.add_argument("--fields", default="")
    m.add_argument("--entity", default="")
    m.add_argument("--index-mode", action="store_true")

    s = sub.add_parser("stream")
    s.add_argument("action", choices=["create"])
    s.add_argument("group")
    s.add_argument("name")
    s.add_argument("--tags", default="")
    s.add_argument("--entity", default="")

    w = sub.add_parser("write")
    w.add_argument("group")
    w.add_argument("name")
    w.add_argument("--point", action="append", default=[], help="JSON data point")
    w.add_argument("--file", help="JSON file: list of points")

    q = sub.add_parser("query")
    q.add_argument("ql", help="BydbQL text")

    ex = sub.add_parser(
        "explain",
        help="run a BydbQL query traced and render the adaptive "
        "planner's decision: chosen path, estimated vs actual rows, "
        "plan tree (docs/performance.md 'Adaptive planner')",
    )
    ex.add_argument("ql", help="BydbQL text")

    rb = sub.add_parser(
        "rebalance",
        help="elastic-cluster shard placement (liaison role; "
        "docs/robustness.md 'Elastic cluster'): plan a minimal part-move "
        "list toward a target topology, apply it live (dual-route "
        "catch-up window, epoch-bumping cutover), or show placement/"
        "repair status",
    )
    rb.add_argument("action", choices=["plan", "apply", "status", "repair"])
    rb.add_argument(
        "--nodes", default="",
        help="comma-separated target node names (default: the liaison's "
        "current discovery addr book — i.e. 'make placement match "
        "membership')",
    )
    rb.add_argument(
        "--replicas", type=int, default=None,
        help="override the replica count in the new placement",
    )

    sl = sub.add_parser(
        "slowlog",
        help="slow-query flight recorder: span trees + plan text of "
        "queries over --slow-query-ms (newest first)",
    )
    sl.add_argument("--limit", type=int, default=20)
    sl.add_argument(
        "--clear", action="store_true", help="drain the ring buffer"
    )
    sl.add_argument(
        "--from-db", action="store_true",
        help="read the persistent self-trace rows from "
        "_monitoring.self_query instead of the in-memory ring "
        "(BYDB_SELF_TRACE; docs/observability.md 'Self-trace')",
    )

    sub.add_parser("metrics", help="Prometheus exposition text")

    sub.add_parser(
        "qos",
        help="multi-tenant QoS status: per-tenant admission counters "
        "(write/query admitted/queued/shed), limits, serving-cache "
        "partitions and in-flight byte charges "
        "(docs/robustness.md 'Multi-tenant QoS')",
    )

    tg = sub.add_parser("trace-get")
    tg.add_argument("group")
    tg.add_argument("name")
    tg.add_argument("trace_id")

    ts = sub.add_parser(
        "trace",
        help="trace query surface: search composes criteria, tag "
        "projection and a sidx ORDER BY into one BydbQL request "
        "(served by standalone and liaison roles)",
    )
    ts.add_argument("action", choices=["search"])
    ts.add_argument("--group", required=True)
    ts.add_argument("--name", required=True)
    ts.add_argument(
        "--where", action="append", default=[],
        help="one condition, e.g. \"svc = 'a'\" or \"dur > 100\" "
        "(repeatable; ANDed)",
    )
    ts.add_argument(
        "--tags", default="*", help="comma-separated tag projection"
    )
    ts.add_argument(
        "--order-by", default="",
        help="sidx-indexed INT tag to order traces by",
    )
    ts.add_argument("--desc", action="store_true")
    ts.add_argument("--limit", type=int, default=20)
    ts.add_argument("--offset", type=int, default=0)
    ts.add_argument(
        "--from-ms", type=int, default=None,
        help="epoch-ms lower bound (inclusive)",
    )
    ts.add_argument(
        "--to-ms", type=int, default=None,
        help="epoch-ms upper bound (exclusive)",
    )

    pr = sub.add_parser("property")
    pr.add_argument("action", choices=["apply", "get", "query"])
    pr.add_argument("group")
    pr.add_argument("name")
    pr.add_argument("id", nargs="?")
    pr.add_argument("--tags", default="{}", help="JSON tag map")

    ins = sub.add_parser("inspect", help="offline on-disk inspection")
    ins.add_argument("--root", help="server root (offline mode)")
    ins.add_argument("--part", help="one part dir for column detail")

    dp = sub.add_parser(
        "dump",
        help="offline part dump (cmd/dump analog): column extents, "
        "block stats, zone-map presence; sidx parts and property shard "
        "indexes have their own formats",
    )
    dp.add_argument(
        "kind", choices=["measure", "stream", "trace", "sidx", "property"],
        help="expected resource kind (validated against part metadata; "
        "property takes a shard-N.idx directory instead of a part dir)",
    )
    dp.add_argument(
        "part_dir",
        help="one part-<id> directory (property: one shard-N.idx dir)",
    )

    lc = sub.add_parser(
        "lifecycle",
        help="tier migration agent (banyand-lifecycle CLI analog)",
    )
    lc.add_argument("action", choices=["migrate"])
    lc.add_argument(
        "--node-root", required=True,
        help="hot node root dir (holds the registry; data under <root>/data)",
    )
    lc.add_argument(
        "--target", required=True, help="warm/cold node bus addr host:port"
    )
    lc.add_argument(
        "--older-than", type=int, required=True,
        help="migrate segments whose window ended before this epoch-ms cutoff",
    )
    lc.add_argument(
        "--catalog", action="append", default=None,
        choices=["measure", "stream", "trace"],
        help="restrict to catalog(s) (repeatable)",
    )

    args = ap.parse_args(argv)

    if args.cmd == "health":
        print(json.dumps(_call(args, Topic.HEALTH.value, {})))
    elif args.cmd == "snapshot":
        print(json.dumps(_call(args, TOPIC_SNAPSHOT, {})))
    elif args.cmd == "group":
        if args.action == "create":
            item = {
                "name": args.name,
                "catalog": args.catalog,
                "resource_opts": {
                    "shard_num": args.shards,
                    "replicas": args.replicas,
                    "segment_interval": {"num": 1, "unit": "day"},
                    "ttl": {"num": 7, "unit": "day"},
                    "stages": [],
                },
            }
            print(json.dumps(_call(args, TOPIC_REGISTRY, {"op": "create", "kind": "group", "item": item})))
        else:
            print(json.dumps(_call(args, TOPIC_REGISTRY, {"op": "list", "kind": "group"})))
    elif args.cmd == "measure":
        if args.action == "create":
            item = {
                "group": args.group,
                "name": args.name,
                "tags": _parse_specs(args.tags),
                "fields": _parse_specs(args.fields) if args.fields else [],
                "entity": {"tag_names": args.entity.split(",") if args.entity else []},
                "interval": "",
                "index_mode": args.index_mode,
            }
            print(json.dumps(_call(args, TOPIC_REGISTRY, {"op": "create", "kind": "measure", "item": item})))
        else:
            print(json.dumps(_call(args, TOPIC_REGISTRY, {"op": "list", "kind": "measure", "group": args.group})))
    elif args.cmd == "stream":
        item = {
            "group": args.group,
            "name": args.name,
            "tags": _parse_specs(args.tags),
            "entity": args.entity.split(",") if args.entity else [],
        }
        print(json.dumps(_call(args, TOPIC_REGISTRY, {"op": "create_stream", "kind": "stream", "item": item})))
    elif args.cmd == "write":
        points = [json.loads(p) for p in args.point]
        if args.file:
            with open(args.file) as fh:
                points += json.loads(fh.read())
        env = {
            "request": {
                "group": args.group,
                "name": args.name,
                "points": [
                    {
                        "ts": p["ts"],
                        "tags": p.get("tags", {}),
                        "fields": p.get("fields", {}),
                        "version": p.get("version", 0),
                    }
                    for p in points
                ],
            }
        }
        print(json.dumps(_call(args, Topic.MEASURE_WRITE.value, env)))
    elif args.cmd == "query":
        print(json.dumps(_call(args, TOPIC_QL, {"ql": args.ql}), indent=1))
    elif args.cmd == "explain":
        reply = _call(args, TOPIC_QL, {"ql": args.ql, "trace": True})
        print(render_explain(reply))
    elif args.cmd == "rebalance":
        env = {"op": args.action}
        if args.nodes:
            env["nodes"] = [n for n in args.nodes.split(",") if n]
        if args.replicas is not None:
            env["replicas"] = args.replicas
        print(json.dumps(_call(args, "rebalance", env), indent=1))
    elif args.cmd == "slowlog":
        if args.from_db:
            ql = SELF_QUERY_QL.format(limit=args.limit)
            print(json.dumps(_call(args, TOPIC_QL, {"ql": ql}), indent=1))
        else:
            env = {"limit": args.limit}
            if args.clear:
                env["clear"] = True
            print(json.dumps(_call(args, TOPIC_SLOWLOG, env), indent=1))
    elif args.cmd == "metrics":
        print(_call(args, TOPIC_METRICS, {})["prometheus"], end="")
    elif args.cmd == "qos":
        from banyandb_tpu.server import TOPIC_QOS

        print(json.dumps(_call(args, TOPIC_QOS, {}), indent=1))
    elif args.cmd == "trace":
        ql = trace_search_ql(
            args.group, args.name,
            tags=args.tags, where=args.where,
            order_by=args.order_by, desc=args.desc,
            limit=args.limit, offset=args.offset,
            from_ms=args.from_ms, to_ms=args.to_ms,
        )
        print(json.dumps(_call(args, TOPIC_QL, {"ql": ql}), indent=1))
    elif args.cmd == "trace-get":
        print(json.dumps(_call(args, Topic.TRACE_QUERY_BY_ID.value, {
            "group": args.group, "name": args.name, "trace_id": args.trace_id,
        }), indent=1))
    elif args.cmd == "property":
        if args.action in ("apply", "get") and not args.id:
            print(f"property {args.action} requires an id", file=sys.stderr)
            return 2
        if args.action == "apply":
            print(json.dumps(_call(args, Topic.PROPERTY_APPLY.value, {
                "group": args.group, "name": args.name, "id": args.id,
                "tags": json.loads(args.tags),
            })))
        elif args.action == "get":
            print(json.dumps(_call(args, Topic.PROPERTY_QUERY.value, {
                "group": args.group, "name": args.name, "id": args.id,
            })))
        else:
            print(json.dumps(_call(args, Topic.PROPERTY_QUERY.value, {
                "group": args.group, "name": args.name,
            }), indent=1))
    elif args.cmd == "inspect":
        from banyandb_tpu.admin.inspect import inspect_part, inspect_root

        if args.part:
            print(json.dumps(inspect_part(args.part), indent=1))
        elif args.root:
            print(json.dumps(inspect_root(args.root), indent=1))
        else:
            print("inspect needs --root or --part", file=sys.stderr)
            return 2
    elif args.cmd == "dump":
        from banyandb_tpu.admin.inspect import (
            inspect_part,
            inspect_property_index,
        )

        if args.kind == "property":
            try:
                doc = inspect_property_index(args.part_dir)
            except (ValueError, KeyError, OSError) as e:
                # an inconsistent index (manifest-listed segment gone,
                # malformed manifest entry) must exit 2 like a non-index
                # dir, not traceback on the operator
                print(f"dump: {e}", file=sys.stderr)
                return 2
            print(json.dumps(doc, indent=1))
            return 0
        doc = inspect_part(args.part_dir)
        if doc["meta"].get(args.kind) is None:
            print(
                f"dump: {args.part_dir} is not a {args.kind} part "
                f"(meta: {sorted(doc['meta'])})",
                file=sys.stderr,
            )
            return 2
        print(json.dumps(doc, indent=1))
    elif args.cmd == "lifecycle":
        # offline agent form, like the reference's standalone lifecycle
        # CLI: open the node's storage directly (the node process must
        # not be running against the same root) and ship over gRPC
        from pathlib import Path

        from banyandb_tpu.admin.tier_migration import TierMigrator
        from banyandb_tpu.api.schema import SchemaRegistry
        from banyandb_tpu.cluster.data_node import DataNode

        root = Path(args.node_root)
        if not (root / "data").exists():
            # a typo'd root must not read as "ran, nothing expired"
            print(f"no data dir under node root {root}", file=sys.stderr)
            return 2
        # the offline agent opens storage and may run query kernels:
        # share the node's persistent XLA compile cache
        from banyandb_tpu.utils import compile_cache

        compile_cache.enable(root / "compile-cache")
        # refuse a root whose owning node process is still alive: a
        # second Shard owner over the same dirs loses in-flight writes
        pid_file = root / "data" / ".bydb-node.pid"
        if pid_file.exists():
            import os

            try:
                owner = int(pid_file.read_text())
            except ValueError:
                owner = 0
            if owner and owner != os.getpid():
                try:
                    os.kill(owner, 0)
                except ProcessLookupError:
                    pass  # stale record from a dead process
                else:  # alive (PermissionError = alive under another uid)
                    print(
                        f"node process pid={owner} is still running on "
                        f"{root}; stop it before offline migration",
                        file=sys.stderr,
                    )
                    return 2
        node = DataNode("lifecycle-agent", SchemaRegistry(root), root / "data")
        transport = GrpcTransport()
        try:
            stats = TierMigrator(node, transport, args.target).run(
                args.older_than,
                catalogs=tuple(args.catalog) if args.catalog else None,
            )
        finally:
            transport.close()
        print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
