"""Streaming dataflow: event-time windows over pushed elements.

Analog of the reference's pkg/flow streaming DAG
(/root/reference/pkg/flow/streaming/streaming.go New/Filter/Map/Window/
To with TumblingTimeWindows + SlidingTimeWindows and event-time
triggers), re-shaped for this runtime: a push-based pipeline where
elements buffer per key and window-fire is driven by an explicit
watermark (the caller's event-time clock), with aggregation done as
vectorized numpy passes over the fired batch instead of per-element
accumulator objects — the same batch-first philosophy as the query
plane.

    flow = (Flow("cpm")
            .filter(lambda e: e.value > 0)
            .map(lambda e: e._replace(value=e.value * 2))
            .key_by(lambda e: e.tags["svc"])
            .window(SlidingEventTimeWindow(size_ms=60_000, slide_ms=15_000))
            .aggregate("sum")
            .to(collector.append))
    flow.feed(elements)                 # any order within lateness
    flow.advance_watermark(ts_millis)   # fires windows ending <= wm

TopN rides the same machinery (models/topn.py keeps its specialized
pre-aggregation path; this module is the general-purpose surface).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, NamedTuple, Optional

import numpy as np


import types

_NO_TAGS = types.MappingProxyType({})


class Element(NamedTuple):
    ts_millis: int
    value: float
    # immutable default: a shared {} would alias every tag-less element
    tags: dict = _NO_TAGS


@dataclass(frozen=True)
class TumblingEventTimeWindow:
    size_ms: int

    def assign(self, ts: int) -> list[int]:
        return [ts - (ts % self.size_ms)]

    @property
    def length_ms(self) -> int:
        return self.size_ms


@dataclass(frozen=True)
class SlidingEventTimeWindow:
    """Overlapping windows: each element lands in size/slide windows
    (flow/streaming/sliding_window.go analog)."""

    size_ms: int
    slide_ms: int

    def __post_init__(self):
        assert self.size_ms % self.slide_ms == 0, "size must be a slide multiple"

    def assign(self, ts: int) -> list[int]:
        last = ts - (ts % self.slide_ms)
        first = last - self.size_ms + self.slide_ms
        return list(range(first, last + 1, self.slide_ms))

    @property
    def length_ms(self) -> int:
        return self.size_ms


@dataclass
class WindowResult:
    start_ms: int
    end_ms: int
    key: object
    value: object  # scalar for count/sum/mean/min/max; list for topn


_AGGS = {
    "count": lambda v: float(len(v)),
    "sum": lambda v: float(np.sum(v)),
    "mean": lambda v: float(np.mean(v)) if len(v) else 0.0,
    "min": lambda v: float(np.min(v)) if len(v) else float("inf"),
    "max": lambda v: float(np.max(v)) if len(v) else float("-inf"),
}


class Flow:
    def __init__(self, name: str):
        self.name = name
        self._filters: list[Callable] = []
        self._maps: list[Callable] = []
        self._key_fn: Callable = lambda e: None
        self._window = None
        self._agg: Optional[str] = None
        self._topn: Optional[tuple[int, bool]] = None
        self._sinks: list[Callable] = []
        self._allowed_lateness_ms = 0
        # open windows: (window_start, key) -> list[value]
        self._open: dict[tuple[int, object], list[float]] = {}
        self._watermark = -(1 << 62)

    # -- builder ------------------------------------------------------------
    def filter(self, fn: Callable) -> "Flow":
        self._filters.append(fn)
        return self

    def map(self, fn: Callable) -> "Flow":
        self._maps.append(fn)
        return self

    def key_by(self, fn: Callable) -> "Flow":
        self._key_fn = fn
        return self

    def window(self, w) -> "Flow":
        self._window = w
        return self

    def allowed_lateness(self, ms: int) -> "Flow":
        self._allowed_lateness_ms = ms
        return self

    def aggregate(self, fn: str) -> "Flow":
        if fn not in _AGGS:
            raise ValueError(f"unknown aggregate {fn!r}")
        self._agg = fn
        return self

    def top_n(self, n: int, desc: bool = True) -> "Flow":
        """Per-window ranking of keys by their aggregated value (requires
        aggregate(...) too; emits one WindowResult per window with a
        ranked [(key, value)] list)."""
        self._topn = (n, desc)
        return self

    def to(self, sink: Callable[[WindowResult], None]) -> "Flow":
        self._sinks.append(sink)
        return self

    # -- runtime ------------------------------------------------------------
    def feed(self, elements) -> int:
        """Push elements (any order within lateness); returns accepted
        count.  Elements at or before the watermark minus lateness are
        DROPPED (their windows already fired — reopening would emit
        duplicates, the same contract as the TopN tumbling windows)."""
        if self._window is None or self._agg is None:
            raise RuntimeError("window(...) and aggregate(...) must be set")
        accepted = 0
        for e in elements:
            ok = True
            for f in self._filters:
                if not f(e):
                    ok = False
                    break
            if not ok:
                continue
            for m in self._maps:
                e = m(e)
            late_cutoff = self._watermark - self._allowed_lateness_ms
            size = self._window.length_ms
            # per-start skip: an element may still belong to OPEN sliding
            # windows while its earlier windows already fired — appending
            # to a fired start would re-fire that window with a partial
            # duplicate
            starts = [
                s
                for s in self._window.assign(e.ts_millis)
                if s + size > late_cutoff
            ]
            if not starts:
                continue  # every window containing it has fired
            key = self._key_fn(e)
            for start in starts:
                self._open.setdefault((start, key), []).append(e.value)
            accepted += 1
        return accepted

    def advance_watermark(self, ts_millis: int) -> list[WindowResult]:
        """Move event time forward; fire every window whose end is at or
        before (watermark - allowed lateness).  Fired results go to the
        sinks and are returned."""
        self._watermark = max(self._watermark, ts_millis)
        cutoff = self._watermark - self._allowed_lateness_ms
        size = self._window.length_ms
        fired: dict[int, dict[object, np.ndarray]] = {}
        for (start, key), vals in list(self._open.items()):
            if start + size <= cutoff:
                fired.setdefault(start, {})[key] = np.asarray(vals)
                del self._open[(start, key)]
        out: list[WindowResult] = []
        agg = _AGGS[self._agg]
        for start in sorted(fired):
            per_key = {k: agg(v) for k, v in fired[start].items()}
            if self._topn is not None:
                n, desc = self._topn
                ranked = sorted(
                    per_key.items(), key=lambda kv: kv[1], reverse=desc
                )[:n]
                out.append(WindowResult(start, start + size, None, ranked))
            else:
                out.extend(
                    WindowResult(start, start + size, k, v)
                    for k, v in sorted(per_key.items(), key=lambda kv: str(kv[0]))
                )
        for r in out:
            for sink in self._sinks:
                sink(r)
        return out
